"""Blockwise (flash) attention — Pallas TPU kernels, forward AND backward.

The reference has no custom kernels (all GPU compute goes through torch
modules); on TPU the attention inner loop is the one op worth hand-writing:
the naive path materializes the [S, S] score matrix in HBM, while these
kernels stream K/V blocks through VMEM with the online-softmax recurrence,
keeping HBM traffic linear in S in BOTH directions:

  forward:  online softmax, emits O and the row logsumexp (LSE, stored
            lane-broadcast [BH, S, 128] following the layout the TPU memory
            system wants for per-row scalars).
  backward: standard two-pass recompute —
              dq kernel   grid (BH, q_blocks, kv_blocks), kv innermost,
                          accumulates dq for one q block across kv blocks;
              dk/dv kernel grid (BH, kv_blocks, q_blocks), q innermost,
                          accumulates dk/dv for one kv block across q blocks.
            Each recomputes p = exp(s - lse) from the saved LSE — no [S, S]
            residual ever touches HBM.

Supports an additive attention bias ([H, S, S] — ALiBi for the Bloom family)
and bidirectional (non-causal) attention for encoder models. The bias is
treated as a constant (stop_gradient): for ALiBi it is position-only, so the
zero cotangent is exact; learned biases must use the XLA path.

Layout notes: head dim is padded to the 128-lane width and sequence to the
block size outside the kernels; zero padding is exact (padded q rows are
sliced off, padded k columns are causally masked or explicitly masked in the
non-causal case, and padded dO rows are zero so they contribute nothing to
dk/dv).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9

BLOCK_Q = 128
BLOCK_K = 128
LANE = 128


def _block_relevant(qi, ki, causal: bool):
    """Whether kv block ki overlaps the causal support of q block qi."""
    if not causal:
        return True
    return ki * BLOCK_K <= qi * BLOCK_Q + (BLOCK_Q - 1)


def _scores(q, k, qi, ki, scale, bias_ref, slope_ref, *, causal: bool,
            kv_len: int):
    """[Bq, Bk] masked, scaled, biased f32 logits for one (q, kv) block pair.

    Operands stay in their native dtype (bf16 in production) so the MXU runs
    at full rate; only the accumulator is f32. ALiBi arrives as a per-head
    SLOPE scalar (slope_ref) and the bias block is generated in-kernel from
    the position iotas — no [H, S, S] bias buffer ever exists in HBM, the
    long-context memory hazard a materialized bias reintroduces.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    q_pos = qi * BLOCK_Q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ki * BLOCK_K + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if slope_ref is not None:
        # Identical to alibi_bias_from_slopes: -slope * (q - k) causal,
        # -slope * |q - k| bidirectional (the signed form would reward
        # future keys in the encoder case).
        dist = (q_pos - k_pos).astype(jnp.float32)
        if not causal:
            dist = jnp.abs(dist)
        s = s - slope_ref[0, 0, 0] * dist
    if causal:
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    else:
        # Padded kv columns are not causally masked in the encoder form —
        # mask them explicitly so softmax never sees them.
        s = jnp.where(k_pos < kv_len, s, NEG_INF)
    return s


def _fwd_kernel(*refs, scale: float, blocks_k: int, causal: bool,
                has_bias: bool, has_slopes: bool, kv_len: int,
                emit_lse: bool):
    refs = list(refs)
    bias_ref = slope_ref = lse_ref = None
    q_ref, k_ref, v_ref = refs[:3]
    del refs[:3]
    if has_bias:
        bias_ref = refs.pop(0)
    if has_slopes:
        slope_ref = refs.pop(0)
    o_ref = refs.pop(0)
    if emit_lse:
        lse_ref = refs.pop(0)
    acc_ref, m_ref, l_ref = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Fully-masked blocks contribute exactly zero — predicate away the MXU
    # work (the actual cost), ~halving causal FLOPs.
    @pl.when(_block_relevant(qi, ki, causal))
    def _():
        q = q_ref[0]                               # [Bq, D] native dtype
        k = k_ref[0]                               # [Bk, D]
        v = v_ref[0]                               # [Bk, D]
        s = _scores(q, k, qi, ki, scale, bias_ref, slope_ref,
                    causal=causal, kv_len=kv_len)

        m_prev = m_ref[:, :1]                      # [Bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [Bq, Bk] f32
        correction = jnp.exp(m_prev - m_new)       # [Bq, 1]

        l_new = l_ref[:, :1] * correction + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == blocks_k - 1)
    def _():
        # Padded-out rows can have l == 0; guard the divide/log.
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        if emit_lse:
            lse_ref[0] = jnp.broadcast_to(m_ref[:, :1] + jnp.log(l),
                                          lse_ref.shape[1:])


def _dq_kernel(*refs, scale: float, blocks_k: int, causal: bool,
               has_bias: bool, has_slopes: bool, kv_len: int):
    refs = list(refs)
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = refs[:6]
    del refs[:6]
    bias_ref = refs.pop(0) if has_bias else None
    slope_ref = refs.pop(0) if has_slopes else None
    dq_ref, dq_acc = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_block_relevant(qi, ki, causal))
    def _():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]     # native dtype (MXU-rate dots)
        do, o = do_ref[0], o_ref[0]
        s = _scores(q, k, qi, ki, scale, bias_ref, slope_ref,
                    causal=causal, kv_len=kv_len)
        p = jnp.exp(s - lse_ref[0][:, :1])         # [Bq, Bk] f32
        dp = jax.lax.dot_general(                  # dO @ V^T  [Bq, Bk]
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)    # [Bq, 1]
        ds = p * (dp - delta)                      # dlogits  [Bq, Bk] f32
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ki == blocks_k - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale: float, blocks_q: int, causal: bool,
                has_bias: bool, has_slopes: bool, kv_len: int):
    refs = list(refs)
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = refs[:6]
    del refs[:6]
    bias_ref = refs.pop(0) if has_bias else None
    slope_ref = refs.pop(0) if has_slopes else None
    dk_ref, dv_ref, dk_acc, dv_acc = refs
    ki = pl.program_id(1)   # kv block is the OUTER sequential axis here
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_relevant(qi, ki, causal))
    def _():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]     # native dtype (MXU-rate dots)
        do, o = do_ref[0], o_ref[0]
        s = _scores(q, k, qi, ki, scale, bias_ref, slope_ref,
                    causal=causal, kv_len=kv_len)
        p = jnp.exp(s - lse_ref[0][:, :1])         # [Bq, Bk] f32
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(   # P^T @ dO  [Bk, D]
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        ds = p * (dp - delta)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(   # dS^T @ Q  [Bk, D]
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(qi == blocks_q - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pad_inputs(q, k, v, bias):
    """Pad head dim to the lane width and seq to the block size."""
    b, h, s_len, d = q.shape
    d_pad = (LANE - d % LANE) % LANE
    s_pad = (BLOCK_Q - s_len % BLOCK_Q) % BLOCK_Q
    if d_pad or s_pad:
        pad = ((0, 0), (0, 0), (0, s_pad), (0, d_pad))
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
        if bias is not None:
            bias = jnp.pad(bias, ((0, 0), (0, s_pad), (0, s_pad)))
    bh = b * h
    sp, dp = q.shape[2], q.shape[3]
    q, k, v = (x.reshape(bh, sp, dp) for x in (q, k, v))
    return q, k, v, bias, (b, h, s_len, d, bh, sp, dp)


def _canon_bias(bias, h, s_len):
    """Canonicalize a broadcastable bias to [H, S, S] (ALiBi form)."""
    if bias is None:
        return None
    bias = jnp.asarray(bias)
    if bias.ndim == 4:
        if bias.shape[0] != 1:
            raise ValueError(
                "flash kernel supports batch-independent bias only "
                f"(got shape {bias.shape}); use the XLA path")
        bias = bias[0]
    return jnp.broadcast_to(bias, (h, s_len, s_len))


def _interpret() -> bool:
    # Interpreter mode off-TPU: tests validate kernel math on the CPU mesh.
    from oobleck_tpu.ops.attention import _pallas_ok

    return not _pallas_ok()


def _bias_specs(has_bias: bool, h: int, outer_is_q: bool):
    if not has_bias:
        return []
    if outer_is_q:
        index = lambda b_, qi, ki: (b_ % h, qi, ki)
    else:
        index = lambda b_, ki, qi: (b_ % h, qi, ki)
    return [pl.BlockSpec((1, BLOCK_Q, BLOCK_K), index)]


def _slope_specs(has_slopes: bool, h: int):
    # One f32 scalar per head, shaped [H, 1, 1]; the grid's batch*head axis
    # indexes its head row (same map under both backward grids — the block
    # index ignores qi/ki).
    if not has_slopes:
        return []
    return [pl.BlockSpec((1, 1, 1), lambda b_, i, j: (b_ % h, 0, 0))]


def _flash_forward(q, k, v, bias, slopes, scale: float, causal: bool,
                   emit_lse: bool = True):
    bias = _canon_bias(bias, q.shape[1], q.shape[2])
    q, k, v, bias, (b, h, s_len, d, bh, sp, dp) = _pad_inputs(q, k, v, bias)
    blocks_q = sp // BLOCK_Q
    blocks_k = sp // BLOCK_K
    has_bias = bias is not None
    has_slopes = slopes is not None
    if has_slopes:
        slopes = jnp.asarray(slopes, jnp.float32).reshape(h, 1, 1)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, blocks_k=blocks_k, causal=causal,
        has_bias=has_bias, has_slopes=has_slopes, kv_len=s_len,
        emit_lse=emit_lse)
    qkv_specs = [
        pl.BlockSpec((1, BLOCK_Q, dp), lambda b_, qi, ki: (b_, qi, 0)),
        pl.BlockSpec((1, BLOCK_K, dp), lambda b_, qi, ki: (b_, ki, 0)),
        pl.BlockSpec((1, BLOCK_K, dp), lambda b_, qi, ki: (b_, ki, 0)),
    ]
    o_spec = pl.BlockSpec((1, BLOCK_Q, dp), lambda b_, qi, ki: (b_, qi, 0))
    o_shape = jax.ShapeDtypeStruct((bh, sp, dp), q.dtype)
    if emit_lse:
        # The LSE residual is only needed when a backward pass will run;
        # forward-only (eval) calls skip the extra [BH, S, 128] HBM write.
        out_shape = (o_shape, jax.ShapeDtypeStruct((bh, sp, LANE), jnp.float32))
        out_specs = (o_spec, pl.BlockSpec((1, BLOCK_Q, LANE),
                                          lambda b_, qi, ki: (b_, qi, 0)))
    else:
        out_shape, out_specs = o_shape, o_spec
    result = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(bh, blocks_q, blocks_k),
        in_specs=(qkv_specs + _bias_specs(has_bias, h, outer_is_q=True)
                  + _slope_specs(has_slopes, h)),
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((BLOCK_Q, dp), jnp.float32),
            pltpu.VMEM((BLOCK_Q, LANE), jnp.float32),
            pltpu.VMEM((BLOCK_Q, LANE), jnp.float32),
        ],
        interpret=_interpret(),
    )(*([q, k, v] + ([bias] if has_bias else [])
        + ([slopes] if has_slopes else [])))

    out, lse = result if emit_lse else (result, None)
    out = out.reshape(b, h, sp, dp)[:, :, :s_len, :d]
    return out, lse


def _flash_backward(q, k, v, bias, slopes, out, lse, g, scale: float,
                    causal: bool):
    bias = _canon_bias(bias, q.shape[1], q.shape[2])
    dtype_in = (q.dtype, k.dtype, v.dtype)
    qp, kp, vp, bias, (b, h, s_len, d, bh, sp, dp) = _pad_inputs(q, k, v, bias)
    # Pad O / dO the same way (their padded rows are zero, so padded-row
    # contributions to dk/dv vanish and padded delta rows are zero).
    op, gp, *_ = _pad_inputs(out, g, g, None)[:2]
    blocks_q = sp // BLOCK_Q
    blocks_k = sp // BLOCK_K
    has_bias = bias is not None
    has_slopes = slopes is not None
    if has_slopes:
        slopes = jnp.asarray(slopes, jnp.float32).reshape(h, 1, 1)
    interpret = _interpret()

    common = ([qp, kp, vp, op, gp, lse] + ([bias] if has_bias else [])
              + ([slopes] if has_slopes else []))

    def qspec(inner_kv: bool):
        # index maps for (q-like, kv-like, lse) inputs under the two grids
        if inner_kv:  # grid (bh, qi, ki)
            qix = lambda b_, qi, ki: (b_, qi, 0)
            kix = lambda b_, qi, ki: (b_, ki, 0)
        else:         # grid (bh, ki, qi)
            qix = lambda b_, ki, qi: (b_, qi, 0)
            kix = lambda b_, ki, qi: (b_, ki, 0)
        return [
            pl.BlockSpec((1, BLOCK_Q, dp), qix),     # q
            pl.BlockSpec((1, BLOCK_K, dp), kix),     # k
            pl.BlockSpec((1, BLOCK_K, dp), kix),     # v
            pl.BlockSpec((1, BLOCK_Q, dp), qix),     # o
            pl.BlockSpec((1, BLOCK_Q, dp), qix),     # do
            pl.BlockSpec((1, BLOCK_Q, LANE), qix),   # lse
        ]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, blocks_k=blocks_k,
                          causal=causal, has_bias=has_bias,
                          has_slopes=has_slopes, kv_len=s_len),
        out_shape=jax.ShapeDtypeStruct((bh, sp, dp), jnp.float32),
        grid=(bh, blocks_q, blocks_k),
        in_specs=(qspec(inner_kv=True)
                  + _bias_specs(has_bias, h, outer_is_q=True)
                  + _slope_specs(has_slopes, h)),
        out_specs=pl.BlockSpec((1, BLOCK_Q, dp), lambda b_, qi, ki: (b_, qi, 0)),
        scratch_shapes=[pltpu.VMEM((BLOCK_Q, dp), jnp.float32)],
        interpret=interpret,
    )(*common)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, blocks_q=blocks_q,
                          causal=causal, has_bias=has_bias,
                          has_slopes=has_slopes, kv_len=s_len),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sp, dp), jnp.float32),
            jax.ShapeDtypeStruct((bh, sp, dp), jnp.float32),
        ),
        grid=(bh, blocks_k, blocks_q),
        in_specs=(qspec(inner_kv=False)
                  + _bias_specs(has_bias, h, outer_is_q=False)
                  + _slope_specs(has_slopes, h)),
        out_specs=(
            pl.BlockSpec((1, BLOCK_K, dp), lambda b_, ki, qi: (b_, ki, 0)),
            pl.BlockSpec((1, BLOCK_K, dp), lambda b_, ki, qi: (b_, ki, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((BLOCK_K, dp), jnp.float32),
            pltpu.VMEM((BLOCK_K, dp), jnp.float32),
        ],
        interpret=interpret,
    )(*common)

    def unpad(x, dt):
        return x.reshape(b, h, sp, dp)[:, :, :s_len, :d].astype(dt)

    return unpad(dq, dtype_in[0]), unpad(dk, dtype_in[1]), unpad(dv, dtype_in[2])


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _flash(q, k, v, bias, slopes, scale, causal):
    out, _ = _flash_forward(q, k, v, bias, slopes, scale, causal,
                            emit_lse=False)
    return out


def _flash_fwd(q, k, v, bias, slopes, scale, causal):
    out, lse = _flash_forward(q, k, v, bias, slopes, scale, causal)
    return out, (q, k, v, bias, slopes, out, lse)


def _flash_bwd(scale, causal, res, g):
    q, k, v, bias, slopes, out, lse = res
    dq, dk, dv = _flash_backward(q, k, v, bias, slopes, out, lse, g, scale,
                                 causal)
    # Bias/slopes are constants (ALiBi): position-only, so the zero
    # cotangent is exact. Learned biases must use the XLA path
    # (attention.py routes them).
    dbias = None if bias is None else jnp.zeros_like(bias)
    dslopes = None if slopes is None else jnp.zeros_like(slopes)
    return dq, dk, dv, dbias, dslopes


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    scale: float | None = None,
                    bias: jax.Array | None = None,
                    alibi_slopes: jax.Array | None = None,
                    causal: bool = True) -> jax.Array:
    """Flash attention. [B, H, S, D] -> [B, H, S, D].

    `bias` is an additive [H, S, S] (or broadcastable) logit bias, treated as
    a constant under differentiation (exact for ALiBi). Prefer
    `alibi_slopes` ([H] f32) for ALiBi: the bias block is generated
    IN-KERNEL from the slopes and position iotas, so no O(H S^2) bias
    buffer exists in HBM at any sequence length. `causal=False` gives the
    bidirectional encoder form.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if q.shape[-2] != k.shape[-2]:
        raise ValueError(
            "flash kernel is self-attention only (seq_q == seq_k); "
            "use the XLA path for cross-attention")
    if bias is not None and alibi_slopes is not None:
        raise ValueError("pass bias OR alibi_slopes, not both")
    if bias is not None:
        bias = jax.lax.stop_gradient(bias)
    if alibi_slopes is not None:
        if alibi_slopes.shape != (q.shape[1],):
            raise ValueError(
                f"alibi_slopes must be [H]={q.shape[1]}, got "
                f"{alibi_slopes.shape}")
        alibi_slopes = jax.lax.stop_gradient(alibi_slopes)
    return _flash(q, k, v, bias, alibi_slopes, scale, causal)
