"""Ragged paged decode attention — block-table KV gather, Pallas + XLA.

The dense serving cache (`[L, slots, H, max_seq, D]`) makes HBM per slot
scale with max_seq and makes every decode step attend over max_seq of
padding. Here K/V live in a pool of fixed-size PAGES (`[N_pages, Hkv,
page, D]` per layer) and each request owns a small chain of pages named
by a block table; decode gathers keys THROUGH the table and masks to the
request's true length (ragged batch — no padding attended, no per-slot
max_seq reservation).

Two implementations behind the `select_attention_impl` seam
(ops/attention.py resolves "paged" to `paged_decode_attention`):

  - XLA reference: gather the table's pages into a contiguous [B, Hkv,
    P*page, D] view and run masked softmax. Shape-identical to the
    kernel output; the correctness oracle for tests.
  - Pallas TPU kernel: the block table and lengths ride as SCALAR
    PREFETCH operands, so each grid step DMAs exactly one live page from
    HBM into VMEM (`BlockSpec` index map reads the table) and the online
    softmax streams pages — the gathered [B, P*page] intermediate never
    exists in HBM. Pages past the request's length are predicated away,
    so a short request costs its true length, not max_seq.

Both support grouped-query caches (Hq a multiple of Hkv: query heads
fold into groups against the unrepeated pool) and ALiBi slopes.
`paged_cache_write` is the matching one-token-per-lane scatter.

Speculative decode adds the MULTI-QUERY verify pair: `paged_verify_attention`
scores T = k+1 candidate positions per lane against the pool in one call
(query row i of lane b sits at absolute position lengths[b]-1+i and
attends keys < lengths[b]+i — masking, GQA folding and ALiBi true
distance identical to decode, of which T=1 is the exact special case),
and `paged_cache_write_multi` is the matching T-token scatter whose
padded rows land on the reserved garbage page.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e9
LANE = 128


# -- block-table plumbing ------------------------------------------------ #

def paged_gather_kv(pool: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Materialize block-table chains from a page pool.

    pool [N, Hkv, page, D]; block_tables [B, P] int32 -> [B, Hkv, P*page, D]
    (position p*page+i of row b is entry i of page block_tables[b, p]).
    """
    b, p = block_tables.shape
    _, hkv, page, d = pool.shape
    gathered = pool[block_tables]                 # [B, P, Hkv, page, D]
    gathered = gathered.transpose(0, 2, 1, 3, 4)  # [B, Hkv, P, page, D]
    return gathered.reshape(b, hkv, p * page, d)


def paged_cache_write(pool: jax.Array, new: jax.Array,
                      block_tables: jax.Array, pos: jax.Array) -> jax.Array:
    """Write one token's K or V per lane into its block-table page.

    pool [N, Hkv, page, D]; new [B, Hkv, D]; block_tables [B, P]; pos [B]
    (lane b's token sits at logical position pos[b], i.e. page
    block_tables[b, pos[b] // page] offset pos[b] % page). Lanes that
    share a page id (inactive lanes parked on the reserved garbage page)
    scatter in lane order; live lanes never alias by construction.
    Safe to donate."""
    page = pool.shape[2]
    b = new.shape[0]
    page_idx = jnp.take_along_axis(
        block_tables, (pos // page)[:, None], axis=1)[:, 0]    # [B]
    off = pos % page
    return pool.at[page_idx, :, off, :].set(
        new.astype(pool.dtype), mode="drop")


def paged_cache_write_multi(pool: jax.Array, new: jax.Array,
                            block_tables: jax.Array, pos: jax.Array,
                            n_live: jax.Array) -> jax.Array:
    """Write T consecutive tokens' K or V per lane through its block table.

    pool [N, Hkv, page, D]; new [B, T, Hkv, D]; block_tables [B, P];
    pos [B] (absolute position of lane b's FIRST token — token i lands at
    pos[b] + i); n_live [B] (tokens i >= n_live[b] are bucket padding and
    scatter to the reserved garbage page 0 instead). The T=1, n_live=1
    case degenerates to `paged_cache_write`. Safe to donate."""
    page = pool.shape[2]
    b, t = new.shape[0], new.shape[1]
    p = block_tables.shape[1]
    i = jnp.arange(t)[None, :]                                 # [1, T]
    pos_abs = pos[:, None] + i                                 # [B, T]
    page_idx = jnp.take_along_axis(
        block_tables, jnp.clip(pos_abs // page, 0, p - 1), axis=1)
    page_idx = jnp.where(i < n_live[:, None], page_idx, 0)  # garbage page
    off = pos_abs % page
    return pool.at[page_idx.reshape(-1), :, off.reshape(-1), :].set(
        new.reshape(b * t, *new.shape[2:]).astype(pool.dtype), mode="drop")


# -- XLA reference ------------------------------------------------------- #

def _paged_decode_xla(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    block_tables: jax.Array, lengths: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
) -> jax.Array:
    """Reference ragged paged decode: gather-then-mask.

    q [B, Hq, D]; pools [N, Hkv, page, D]; block_tables [B, P];
    lengths [B] (keys at positions < lengths[b] are live; the newest
    token's key must already be written, so lengths = pos + 1).
    Returns [B, Hq, D]."""
    b, hq, d = q.shape
    hkv = k_pool.shape[1]
    if scale is None:
        scale = d**-0.5
    g = hq // hkv
    k = paged_gather_kv(k_pool, block_tables)     # [B, Hkv, S, D]
    v = paged_gather_kv(v_pool, block_tables)
    s_len = k.shape[2]
    qg = q.reshape(b, hkv, g, d)
    logits = jnp.einsum("bkgd,bksd->bkgs", qg, k) * scale
    k_idx = jnp.arange(s_len)
    if alibi_slopes is not None:
        dist = ((lengths[:, None] - 1) - k_idx[None, :]).astype(jnp.float32)
        slopes = alibi_slopes.reshape(hkv, g)
        logits = logits - slopes[None, :, :, None] * dist[:, None, None, :]
    live = k_idx[None, :] < lengths[:, None]      # [B, S]
    logits = jnp.where(live[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bkgs,bksd->bkgd", probs, v).reshape(b, hq, d)


def _paged_verify_xla(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    block_tables: jax.Array, lengths: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
) -> jax.Array:
    """Reference ragged multi-query verify: gather-then-mask.

    q [B, T, Hq, D] (T = k+1 speculative positions per lane; all T
    tokens' keys must already be written); pools [N, Hkv, page, D];
    block_tables [B, P]; lengths [B] (live keys for query row 0 — row i
    attends keys at positions < lengths[b] + i, so each draft token sees
    exactly the prefix a sequential decode would have). Returns
    [B, T, Hq, D]; row 0 is bit-compatible with `_paged_decode_xla`."""
    b, t, hq, d = q.shape
    hkv = k_pool.shape[1]
    if scale is None:
        scale = d**-0.5
    g = hq // hkv
    k = paged_gather_kv(k_pool, block_tables)     # [B, Hkv, S, D]
    v = paged_gather_kv(v_pool, block_tables)
    s_len = k.shape[2]
    qg = q.reshape(b, t, hkv, g, d).transpose(0, 2, 3, 1, 4)  # [B,Hkv,G,T,D]
    logits = jnp.einsum("bkgtd,bksd->bkgts", qg, k) * scale
    k_idx = jnp.arange(s_len)
    row_len = lengths[:, None] + jnp.arange(t)[None, :]        # [B, T]
    if alibi_slopes is not None:
        dist = ((row_len[:, :, None] - 1)
                - k_idx[None, None, :]).astype(jnp.float32)    # [B, T, S]
        slopes = alibi_slopes.reshape(hkv, g)
        logits = logits - slopes[None, :, :, None, None] * dist[:, None, None]
    live = k_idx[None, None, :] < row_len[:, :, None]          # [B, T, S]
    logits = jnp.where(live[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgts,bksd->bkgtd", probs, v)            # [B,Hkv,G,T,D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, t, hq, d)


# -- Pallas kernel ------------------------------------------------------- #

def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                  scale: float, pages: int, page: int, has_slopes: bool):
    """One (lane, kv-head, page) grid step of the streamed decode.

    Scalar-prefetch refs first (block table, lengths), then the VMEM
    blocks. Scratch carries the online-softmax state across the page
    axis (innermost, sequential)."""
    rest = list(rest)
    slope_ref = rest.pop(0) if has_slopes else None
    o_ref = rest.pop(0)
    acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(2)
    length = len_ref[b]

    @pl.when(p == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Pages wholly past the live length contribute nothing — predicate
    # the DMA'd block's compute away so a short request costs its true
    # length. (The ragged win: no max_seq of padding in the loop.)
    @pl.when(p * page < length)
    def _():
        qg = q_ref[0, 0]                           # [G, D] native dtype
        k = k_ref[0, 0]                            # [page, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            qg, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [G, page] f32
        k_pos = p * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if slope_ref is not None:
            dist = ((length - 1) - k_pos).astype(jnp.float32)
            s = s - slope_ref[0, :, :1] * dist
        s = jnp.where(k_pos < length, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * corr + jnp.sum(pexp, axis=-1, keepdims=True),
            l_ref.shape)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == pages - 1)
    def _():
        # Inactive lanes (length 0) never accumulate; guard the divide.
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _interpret() -> bool:
    # Interpreter mode off-TPU, same toggle as the flash kernel.
    from oobleck_tpu.ops.attention import _pallas_ok

    return not _pallas_ok()


def _paged_decode_pallas(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    block_tables: jax.Array, lengths: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
) -> jax.Array:
    """Streamed ragged paged decode (see module docstring). Same contract
    as `_paged_decode_xla`."""
    b, hq, d = q.shape
    n, hkv, page, _ = k_pool.shape
    pages = block_tables.shape[1]
    if scale is None:
        scale = d**-0.5
    g = hq // hkv
    d_pad = (LANE - d % LANE) % LANE
    if d_pad:
        pad4 = ((0, 0), (0, 0), (0, 0), (0, d_pad))
        k_pool = jnp.pad(k_pool, pad4)
        v_pool = jnp.pad(v_pool, pad4)
        q = jnp.pad(q, ((0, 0), (0, 0), (0, d_pad)))
    dp = d + d_pad
    qg = q.reshape(b, hkv, g, dp)
    has_slopes = alibi_slopes is not None

    in_specs = [
        pl.BlockSpec((1, 1, g, dp), lambda bi, h, p, bt, ln: (bi, h, 0, 0)),
        # The block table IS the index map: page p of lane bi comes from
        # pool row bt[bi, p] — the gather never materializes in HBM.
        pl.BlockSpec((1, 1, page, dp),
                     lambda bi, h, p, bt, ln: (bt[bi, p], h, 0, 0)),
        pl.BlockSpec((1, 1, page, dp),
                     lambda bi, h, p, bt, ln: (bt[bi, p], h, 0, 0)),
    ]
    operands = [qg, k_pool, v_pool]
    if has_slopes:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(hkv, g, 1)
        in_specs.append(
            pl.BlockSpec((1, g, 1), lambda bi, h, p, bt, ln: (h, 0, 0)))
        operands.append(slopes)

    # k/v blocks arrive [1, page, dp] (head dim collapsed by the block
    # shape's leading 1s — Pallas drops size-1 block dims only when the
    # BlockSpec says so; keep explicit [1, ...] and index [0] in-kernel).
    kernel = functools.partial(
        _paged_kernel, scale=scale, pages=pages, page=page,
        has_slopes=has_slopes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, dp),
                               lambda bi, h, p, bt, ln: (bi, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, dp), jnp.float32),
            pltpu.VMEM((g, LANE), jnp.float32),
            pltpu.VMEM((g, LANE), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, dp), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32), jnp.asarray(lengths, jnp.int32),
      *operands)
    return out.reshape(b, hq, dp)[:, :, :d]


def _paged_verify_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                         scale: float, pages: int, page: int, t: int,
                         g: int, has_slopes: bool):
    """One (lane, kv-head, page) grid step of the streamed multi-query
    verify. Identical structure to `_paged_kernel`, but the q block
    carries T*G rows (T speculative positions x G grouped query heads)
    and the causal bound is PER ROW: row r's query position is
    length - 1 + r // G, so its live-key bound is length + r // G."""
    rest = list(rest)
    slope_ref = rest.pop(0) if has_slopes else None
    o_ref = rest.pop(0)
    acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    p = pl.program_id(2)
    length = len_ref[b]

    @pl.when(p == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # A page is live iff ANY row can see it — the deepest row (t-1)
    # bounds the predicate; rows that see less mask per-element below.
    @pl.when(p * page < length + t - 1)
    def _():
        qg = q_ref[0, 0]                           # [T*G, D] native dtype
        k = k_ref[0, 0]                            # [page, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            qg, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [T*G, page] f32
        k_pos = p * page + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        row_len = length + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // g
        if slope_ref is not None:
            dist = (row_len - 1 - k_pos).astype(jnp.float32)
            s = s - slope_ref[0, :, :1] * dist
        s = jnp.where(k_pos < row_len, s, NEG_INF)

        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pexp = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * corr + jnp.sum(pexp, axis=-1, keepdims=True),
            l_ref.shape)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            pexp.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == pages - 1)
    def _():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _paged_verify_pallas(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    block_tables: jax.Array, lengths: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
) -> jax.Array:
    """Streamed ragged multi-query verify. Same contract as
    `_paged_verify_xla`."""
    b, t, hq, d = q.shape
    n, hkv, page, _ = k_pool.shape
    pages = block_tables.shape[1]
    if scale is None:
        scale = d**-0.5
    g = hq // hkv
    d_pad = (LANE - d % LANE) % LANE
    if d_pad:
        pad4 = ((0, 0), (0, 0), (0, 0), (0, d_pad))
        k_pool = jnp.pad(k_pool, pad4)
        v_pool = jnp.pad(v_pool, pad4)
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, d_pad)))
    dp = d + d_pad
    # Rows ordered (position, group): row r = i*G + gi.
    qg = q.reshape(b, t, hkv, g, dp).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(b, hkv, t * g, dp)
    has_slopes = alibi_slopes is not None

    in_specs = [
        pl.BlockSpec((1, 1, t * g, dp), lambda bi, h, p, bt, ln: (bi, h, 0, 0)),
        pl.BlockSpec((1, 1, page, dp),
                     lambda bi, h, p, bt, ln: (bt[bi, p], h, 0, 0)),
        pl.BlockSpec((1, 1, page, dp),
                     lambda bi, h, p, bt, ln: (bt[bi, p], h, 0, 0)),
    ]
    operands = [qg, k_pool, v_pool]
    if has_slopes:
        # Row r's slope is slopes[r % G] — tile the [Hkv, G] groups T times.
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(hkv, g, 1)
        slopes = jnp.tile(slopes, (1, t, 1))               # [Hkv, T*G, 1]
        in_specs.append(
            pl.BlockSpec((1, t * g, 1), lambda bi, h, p, bt, ln: (h, 0, 0)))
        operands.append(slopes)

    kernel = functools.partial(
        _paged_verify_kernel, scale=scale, pages=pages, page=page, t=t, g=g,
        has_slopes=has_slopes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, t * g, dp),
                               lambda bi, h, p, bt, ln: (bi, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t * g, dp), jnp.float32),
            pltpu.VMEM((t * g, LANE), jnp.float32),
            pltpu.VMEM((t * g, LANE), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, t * g, dp), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32), jnp.asarray(lengths, jnp.int32),
      *operands)
    out = out.reshape(b, hkv, t, g, dp).transpose(0, 2, 1, 3, 4)
    return out.reshape(b, t, hq, dp)[:, :, :, :d]


# -- dispatch ------------------------------------------------------------ #

@functools.cache
def _select_paged_impl(impl: str = "auto"):
    if impl == "xla":
        return _paged_decode_xla
    if impl == "pallas":
        return _paged_decode_pallas
    if impl == "auto":
        # Same policy as select_attention_impl("auto"): the Pallas kernel
        # on TPU (streamed pages, no HBM gather), the fused XLA gather on
        # CPU where the kernel would run interpreted.
        from oobleck_tpu.ops.attention import _pallas_ok

        if _pallas_ok():
            return _paged_decode_pallas
        return _paged_decode_xla
    raise ValueError(f"unknown paged attention impl: {impl!r}")


def paged_decode_attention(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    block_tables: jax.Array, lengths: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
    impl: str = "auto",
) -> jax.Array:
    """Ragged paged decode attention (dispatching entry point).

    q [B, Hq, D]; k_pool/v_pool [N, Hkv, page, D]; block_tables [B, P]
    int32; lengths [B] int32 (live keys per lane; 0 = inactive lane,
    which computes garbage harmlessly). Grouped-query pools fold query
    heads into [Hkv, G] groups. Returns [B, Hq, D]."""
    hq, hkv = q.shape[1], k_pool.shape[1]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of KV heads {hkv}")
    if alibi_slopes is not None and alibi_slopes.shape != (hq,):
        raise ValueError(
            f"alibi_slopes must be [Hq]={hq}, got {alibi_slopes.shape}")
    fn = _select_paged_impl(impl)
    return fn(q, k_pool, v_pool, block_tables, lengths, scale=scale,
              alibi_slopes=alibi_slopes)


@functools.cache
def _select_paged_verify_impl(impl: str = "auto"):
    if impl == "xla":
        return _paged_verify_xla
    if impl == "pallas":
        return _paged_verify_pallas
    if impl == "auto":
        from oobleck_tpu.ops.attention import _pallas_ok

        if _pallas_ok():
            return _paged_verify_pallas
        return _paged_verify_xla
    raise ValueError(f"unknown paged attention impl: {impl!r}")


def paged_verify_attention(
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    block_tables: jax.Array, lengths: jax.Array, *,
    scale: float | None = None, alibi_slopes: jax.Array | None = None,
    impl: str = "auto",
) -> jax.Array:
    """Ragged multi-query speculative verify (dispatching entry point).

    q [B, T, Hq, D] — T = k+1 candidate positions per lane, all of whose
    K/V must already be written (`paged_cache_write_multi`); lengths [B]
    int32 is the live-key count for query row 0 (= row 0's position + 1),
    and row i attends keys < lengths[b] + i — the exact prefix a
    sequential decode of the accepted tokens would see. Lanes with fewer
    live candidates than T compute garbage in their padded rows
    harmlessly (their writes landed on the garbage page). T=1 is
    `paged_decode_attention` exactly. Returns [B, T, Hq, D]."""
    if q.ndim != 4:
        raise ValueError(f"verify q must be [B, T, Hq, D], got {q.shape}")
    hq, hkv = q.shape[2], k_pool.shape[1]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of KV heads {hkv}")
    if alibi_slopes is not None and alibi_slopes.shape != (hq,):
        raise ValueError(
            f"alibi_slopes must be [Hq]={hq}, got {alibi_slopes.shape}")
    fn = _select_paged_verify_impl(impl)
    return fn(q, k_pool, v_pool, block_tables, lengths, scale=scale,
              alibi_slopes=alibi_slopes)
