"""oobleck_tpu — a TPU-native resilient distributed training framework.

A ground-up JAX/XLA re-design with the capabilities of SymbioticLab/Oobleck
(SOSP '23): fault-tolerant large-model training built on *pipeline templates*.
A planner (per-layer profiler + C++ divide-and-conquer template generator +
batch-distribution solver) precomputes optimal pipeline configurations for
every feasible node count; an elastic master/agent/worker control plane detects
host failures; and the execution engine re-instantiates heterogeneous pipelines
on the survivors and resumes within seconds.

Unlike the reference (PyTorch/DeepSpeed/NCCL), the compute path here is
idiomatic JAX: models are explicit layer lists (no fx tracing), pipeline
stages run as pjit/shard_map computations on TPU sub-meshes, stage-to-stage
activations move with `lax.ppermute` over ICI, and data-parallel gradient sync
uses `lax.psum` / cross-mesh transfers.

Layer map (mirrors reference SURVEY.md §1):
  L5 CLI            oobleck_tpu.elastic.run
  L4 Elastic        oobleck_tpu.elastic (master / agent / worker)
  L3 Planning       oobleck_tpu.planning (+ csrc C++ planner)
  L2 Model / data   oobleck_tpu.models, oobleck_tpu.execution.{dataset,dataloader}
  L1 Execution      oobleck_tpu.execution (engine / pipeline), oobleck_tpu.parallel
"""

__version__ = "0.1.0"
