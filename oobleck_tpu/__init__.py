"""oobleck_tpu — a TPU-native resilient distributed training framework.

A ground-up JAX/XLA re-design with the capabilities of SymbioticLab/Oobleck
(SOSP '23): fault-tolerant large-model training built on *pipeline templates*.
A planner (per-layer profiler + C++ divide-and-conquer template generator +
batch-distribution solver) precomputes optimal pipeline configurations for
every feasible node count; an elastic master/agent/worker control plane detects
host failures; and the execution engine re-instantiates heterogeneous pipelines
on the survivors and resumes within seconds.

Unlike the reference (PyTorch/DeepSpeed/NCCL), the compute path here is
idiomatic JAX: models are explicit layer lists (no fx tracing), pipeline
stages run as pjit/shard_map computations on TPU sub-meshes, stage-to-stage
activations move with `lax.ppermute` over ICI, and data-parallel gradient sync
uses `lax.psum` / cross-mesh transfers.

Layer map (mirrors reference SURVEY.md §1):
  L5 CLI            oobleck_tpu.elastic.run
  L4 Elastic        oobleck_tpu.elastic (master / agent / worker)
  L3 Planning       oobleck_tpu.planning (+ csrc C++ planner)
  L2 Model / data   oobleck_tpu.models, oobleck_tpu.execution.{dataset,dataloader}
  L1 Execution      oobleck_tpu.execution (engine / pipeline), oobleck_tpu.parallel
"""

__version__ = "0.1.0"

# Compat: `jax.shard_map` was promoted out of jax.experimental after 0.4.x;
# on older jaxlib images the top-level name is missing and the experimental
# version spells "which axes are manual" as the complementary `auto` set
# instead of `axis_names`. Install an adapter once at package import so
# every call site (and the tests) uses the one modern spelling.
import jax as _jax

if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _esm

    def _shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                   check_vma=None):
        kw = {}
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kw)

    _jax.shard_map = _shard_map

# Compat: jax 0.4.x defaults `jax_threefry_partitionable` to False, under
# which jitted `jax.random.*` draws take DIFFERENT values depending on the
# output sharding the partitioner picks — so `init_fn` produces different
# initial params on different mesh factorizations, breaking the "same loss
# trajectory on every mesh" invariant (and any cross-mesh checkpoint
# restore comparison). Partitionable threefry makes draws a pure function
# of (key, position), invariant to sharding; it has been the default since
# jax 0.4.36+ and this update is a no-op there.
try:
    _jax.config.update("jax_threefry_partitionable", True)
except (AttributeError, KeyError):
    pass  # flag retired: partitionable is the only behavior

# Compat: jax 0.4.x defaults cross-process CPU collectives to "none", so any
# multi-process CPU world (the test harness's jax.distributed worlds) fails
# with "Multiprocess computations aren't implemented on the CPU backend".
# jaxlib ships a gloo implementation; select it whenever a distributed
# runtime is live (or about to come up) on the CPU platform. The flag only
# matters before the CPU client is instantiated, which is why the update
# rides jax.distributed.initialize — the one call that always precedes the
# first backend touch in a multi-process world.


def _enable_cpu_gloo_collectives() -> None:
    # Local imports: this runs long after module init (module-level helper
    # names are cleaned up below).
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        return
    try:
        from jax._src import xla_bridge as _xb

        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value == "none":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (ImportError, AttributeError):
        pass  # newer jax: gloo is already the default and the flag moved


import jax.distributed as _jd

if not getattr(_jd.initialize, "_oobleck_gloo_wrapped", False):
    _orig_distributed_initialize = _jd.initialize

    def _initialize_with_cpu_gloo(*args, **kwargs):
        _enable_cpu_gloo_collectives()
        return _orig_distributed_initialize(*args, **kwargs)

    _initialize_with_cpu_gloo._oobleck_gloo_wrapped = True
    _jd.initialize = _initialize_with_cpu_gloo

try:
    # Importing oobleck_tpu AFTER jax.distributed.initialize (external test
    # drivers do this) still precedes the first computation: fix the flag now.
    from jax._src import distributed as _dist

    if _dist.global_state.client is not None:
        _enable_cpu_gloo_collectives()
except (ImportError, AttributeError):
    pass
del _jax, _jd
