"""Job configuration schema.

Capability match for the reference's config dataclasses
(/root/reference/oobleck/elastic/training_util.py:8-39), re-shaped for TPU:
`num_workers` means worker processes per *host* (a TPU host owns all its local
chips — there is no per-GPU process pinning), and a TPU-specific `execution`
section carries mesh / precision knobs the reference does not have.

Serialization is plain-dict based (yaml / json safe) so configs can travel the
elastic control plane's wire protocol without pickle.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any

import yaml


@dataclass
class DistributedArguments:
    """Cluster topology and control-plane addressing."""

    master_ip: str = "127.0.0.1"
    master_port: int = 19191
    node_ips: list[str] = field(default_factory=lambda: ["127.0.0.1"])
    node_port: int = 22
    num_workers: int = 1
    num_agents_per_node: int = 1
    username: str | None = None


@dataclass
class JobArguments:
    """Training-run hyperparameters used by the engine and planner."""

    fault_threshold: int = 3
    microbatch_size: int = 8
    global_microbatch_size: int = 128
    steps: int = 50
    learning_rate: float = 1e-4
    warmup_steps: int = 10
    weight_decay: float = 0.01
    max_grad_norm: float = 1.0

    def __post_init__(self) -> None:
        if self.global_microbatch_size % self.microbatch_size != 0:
            raise ValueError(
                "global_microbatch_size must be a multiple of microbatch_size: "
                f"{self.global_microbatch_size} % {self.microbatch_size} != 0"
            )

    @property
    def global_num_microbatch(self) -> int:
        return self.global_microbatch_size // self.microbatch_size


@dataclass
class ModelArguments:
    """Model family + dataset selection.

    `model_name` follows HF naming (e.g. "gpt2", "gpt2-xl") resolved through
    oobleck_tpu.models.registry; `model_args` overrides config fields the same
    way the reference threads them into AutoConfig.
    """

    model_name: str = "gpt2"
    model_tag: str = "default"
    dataset_path: str = "synthetic"
    dataset_name: str | None = None
    model_args: dict[str, Any] = field(default_factory=dict)


@dataclass
class ExecutionArguments:
    """TPU-specific execution knobs (no reference counterpart).

    Every knob here is consumed by the engine:
      * MPMD path: `tensor_parallel`/`sequence_parallel`/`fsdp` factor each
        stage's chips into a (fsdp, seq, tensor) stage mesh; `num_stages`
        filters the feasible pipeline templates; `precision`/`remat`/
        `attention_impl` override model config. Sequence parallelism in a
        stage is Ulysses/ring over the stage-local `seq` axis, so
        long-context and elastic heterogeneous pipelines compose.
      * Fused path (`engine_path: fused`, or `auto` with
        sequence_parallel > 1): one global mesh
        (data, stage, fsdp, seq, tensor) runs the compiled SPMD train step
        (parallel/train.py).
    """

    # Which execution path drives training: "mpmd" (per-stage jits +
    # 1F1B interpreter, supports heterogeneous pipelines), "fused" (one
    # compiled SPMD program over a global mesh), or "auto" (fused when
    # sequence_parallel > 1, mpmd otherwise).
    engine_path: str = "auto"
    # Mesh axis sizes; -1 means "infer".
    num_stages: int = -1          # pipeline-parallel degree (per pipeline)
    tensor_parallel: int = 1      # intra-op model sharding degree
    fsdp: int = -1                # param-sharding degree within a stage (-1: remaining chips)
    sequence_parallel: int = 1    # ring-attention / context-parallel degree
    precision: str = "bfloat16"   # activation/compute dtype
    remat: bool = True            # rematerialize per-layer activations
    attention_impl: str = "auto"  # auto | xla | pallas | ring | ulysses
    checkpoint_dir: str | None = None
    checkpoint_interval: int = 0  # steps; 0 disables
    # Durable-state plane knobs (oobleck_tpu/ckpt). keep_last <= 0 keeps
    # every step; checkpoint_async=False is the synchronous baseline
    # (the train loop stalls for the full device->host->disk write).
    checkpoint_keep_last: int = 3
    checkpoint_async: bool = True
    # Checkpoint-FREE multi-host recovery (reference engine.py:238-309:
    # survivors broadcast live states, no checkpoint reload): each worker
    # mirrors its LOCAL layers' live state to a host-local file every
    # mirror_interval steps; after a failure the respawned world refills
    # every layer from the freshest surviving mirror with one collective,
    # falling back to a checkpoint only for layers no survivor holds.
    # mirror_dir must be HOST-LOCAL storage (e.g. /dev/shm); None disables.
    mirror_dir: str | None = None
    mirror_interval: int = 1
    # Cross-pipeline replica re-broadcast period (steps; 0 disables). DP
    # replicas of a layer drift bitwise over time (different per-mesh
    # reduction orders); the reference re-broadcasts only during failure
    # recovery (_copy_model_states, engine.py:238-309) — here drift is
    # bounded unconditionally, independent of checkpointing.
    replica_sync_interval: int = 100
    # Fraction of the dataset reserved as a held-out tail for evaluate()
    # when no real validation split exists. Nonzero BY DEFAULT so eval is
    # honest out of the box; 0 opts out explicitly (train on everything,
    # the reference behavior — its eval data is never actually driven).
    eval_fraction: float = 0.02
    # Pipeline schedule for the MPMD path: "1f1b" (canonical) or
    # "interleaved" (Megatron-style virtual pipeline — each stage holds
    # virtual_stages model chunks, shrinking the bubble from
    # (S-1)/(M+S-1) to (S-1)/(v*M+S-1)). Interleaving requires the
    # per-pipeline microbatch count to be a multiple of num_stages and at
    # least num_stages*virtual_stages pipeline layers; when a
    # reconfiguration leaves a plan that cannot honor it, the engine falls
    # back to 1f1b and records a flight-recorder event.
    pipeline_schedule: str = "1f1b"
    virtual_stages: int = 1
    # Host loss-readback period (steps). 1 = read every step (the classic
    # contract: per-step log lines, loss gauge per step). N > 1 keeps the
    # loss on-device and resolves N steps at a time, removing the only
    # blocking host sync from the steady-state train loop.
    loss_readback_every: int = 1
    # Bounded-time recovery: how many host losses ahead to AOT-precompile
    # re-planned stage executables for (execution/precompile.py). Depth d
    # walks the plans the instantiator would match after losing 1..d hosts
    # (plus the current plan) and compiles their stage programs into the
    # persistent compilation cache on a background thread, so
    # reconfigure()/respawn deserializes instead of cold-compiling.
    # 0 disables. OOBLECK_PRECOMPILE overrides at runtime.
    precompile_recovery_depth: int = 2
    # Degraded-mode execution plane (oobleck_tpu/degrade): on failure, try
    # rerouting the dead DP replica's microbatches into the survivors'
    # pipeline bubbles BEFORE template re-instantiation — same topology,
    # no re-plan, no recompile (ReCycle, arxiv 2405.14009). A reroute is
    # only taken when the planner projects step-time slowdown <=
    # degrade_max_slowdown; otherwise (or when no DP peer survives) the
    # engine falls back to re-instantiation. OOBLECK_DEGRADE (0/1) and
    # OOBLECK_DEGRADE_MAX_SLOWDOWN override at runtime.
    degrade_enabled: bool = True
    degrade_max_slowdown: float = 4.0
    # Collective/compute overlap on the fused path (parallel/overlap.py):
    # bucketed ppermute-ring grad sync, FSDP gather prefetch, double-buffered
    # cross-stage sends, and XLA async-collective flag passthrough.
    # OOBLECK_OVERLAP, OOBLECK_OVERLAP_BUCKET_MB, OOBLECK_OVERLAP_PREFETCH,
    # OOBLECK_OVERLAP_DB_SENDS, OOBLECK_OVERLAP_XLA_FLAGS override at runtime.
    overlap_enabled: bool = False
    overlap_bucket_bytes: int = 4 * 1024 * 1024
    overlap_prefetch: bool = True
    overlap_db_sends: bool = False
    overlap_xla_flags: bool = True

    def __post_init__(self) -> None:
        if self.engine_path not in ("auto", "mpmd", "fused"):
            raise ValueError(
                f"engine_path must be auto|mpmd|fused, got {self.engine_path!r}"
            )
        if self.attention_impl not in ("auto", "xla", "pallas", "ring",
                                       "ulysses"):
            raise ValueError(
                "attention_impl must be auto|xla|pallas|ring|ulysses, got "
                f"{self.attention_impl!r}"
            )
        if self.pipeline_schedule not in ("1f1b", "interleaved"):
            raise ValueError(
                "pipeline_schedule must be 1f1b|interleaved, got "
                f"{self.pipeline_schedule!r}"
            )
        if self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {self.virtual_stages}"
            )
        if self.pipeline_schedule == "1f1b" and self.virtual_stages > 1:
            raise ValueError(
                "virtual_stages > 1 requires pipeline_schedule: interleaved"
            )
        if self.loss_readback_every < 1:
            raise ValueError(
                f"loss_readback_every must be >= 1, got "
                f"{self.loss_readback_every}"
            )
        if self.degrade_max_slowdown <= 1.0:
            raise ValueError(
                "degrade_max_slowdown must be > 1 (a reroute always costs "
                f"some step time), got {self.degrade_max_slowdown}"
            )
        if self.overlap_bucket_bytes <= 0:
            raise ValueError(
                f"overlap_bucket_bytes must be > 0, got "
                f"{self.overlap_bucket_bytes}"
            )

    @property
    def resolved_virtual_stages(self) -> int:
        return self.virtual_stages if self.pipeline_schedule == "interleaved" else 1

    def apply_durable_env_overrides(self) -> None:
        """Runtime overrides for the durable-state plane — preemption
        notice handling and checkpoint cadence are deployment properties,
        not model properties, so they must be settable without editing the
        job yaml: OOBLECK_CKPT_DIR, OOBLECK_CKPT_INTERVAL,
        OOBLECK_CKPT_KEEP, OOBLECK_CKPT_ASYNC (0/1)."""
        import os

        v = os.environ.get("OOBLECK_CKPT_DIR")
        if v:
            self.checkpoint_dir = v
        v = os.environ.get("OOBLECK_CKPT_INTERVAL")
        if v:
            self.checkpoint_interval = int(v)
        v = os.environ.get("OOBLECK_CKPT_KEEP")
        if v:
            self.checkpoint_keep_last = int(v)
        v = os.environ.get("OOBLECK_CKPT_ASYNC")
        if v:
            self.checkpoint_async = v.lower() not in ("0", "false", "no")
        v = os.environ.get("OOBLECK_DEGRADE")
        if v:
            self.degrade_enabled = v.lower() not in ("0", "false", "no")
        v = os.environ.get("OOBLECK_DEGRADE_MAX_SLOWDOWN")
        if v:
            self.degrade_max_slowdown = float(v)
        v = os.environ.get("OOBLECK_OVERLAP")
        if v:
            self.overlap_enabled = v.lower() not in ("0", "false", "no")
        v = os.environ.get("OOBLECK_OVERLAP_BUCKET_MB")
        if v:
            self.overlap_bucket_bytes = int(float(v) * 1024 * 1024)
        v = os.environ.get("OOBLECK_OVERLAP_PREFETCH")
        if v:
            self.overlap_prefetch = v.lower() not in ("0", "false", "no")
        v = os.environ.get("OOBLECK_OVERLAP_DB_SENDS")
        if v:
            self.overlap_db_sends = v.lower() not in ("0", "false", "no")
        v = os.environ.get("OOBLECK_OVERLAP_XLA_FLAGS")
        if v:
            self.overlap_xla_flags = v.lower() not in ("0", "false", "no")

    def overlap_config(self):
        """The parallel.overlap.OverlapConfig these arguments describe."""
        from oobleck_tpu.parallel.overlap import OverlapConfig

        return OverlapConfig(
            enabled=self.overlap_enabled,
            bucket_bytes=self.overlap_bucket_bytes,
            prefetch_fsdp=self.overlap_prefetch,
            double_buffer_sends=self.overlap_db_sends,
            xla_flags=self.overlap_xla_flags,
        )

    def resolved_path(self) -> str:
        # auto: fused is still the default home for sequence parallelism
        # (single compiled program); explicit `engine_path: mpmd` +
        # sequence_parallel > 1 runs seq-parallel stage meshes instead.
        if self.engine_path != "auto":
            return self.engine_path
        return "fused" if self.sequence_parallel > 1 else "mpmd"


@dataclass
class ServeArguments:
    """Elastic serving plane knobs (oobleck_tpu/serve).

    The server consumes the durable-state plane's checkpoint root
    (`execution.checkpoint_dir` / OOBLECK_CKPT_DIR) and hot-reloads the
    newest committed step while serving."""

    port: int = 0                 # HTTP port; 0 = ephemeral (tests)
    slots: int = 4                # dense slots / paged memory-budget unit
    max_seq: int = 256            # per-request length cap (prompt + gen)
    max_queue: int = 64           # bounded admission queue; full -> reject
    reload_secs: float = 5.0      # checkpoint-watcher poll period
    max_tokens_default: int = 64  # per-request cap when unspecified
    # Paged KV cache (serve/kv_blocks.py + ops/paged_attention.py).
    # "paged" is the default discipline; "dense" restores the slot cache.
    kv_cache: str = "paged"
    page_size: int = 16           # tokens per KV pool page
    kv_pages: int = 0             # pool pages incl. garbage page; 0 = auto
    #                               (slots * max_seq / page_size — the same
    #                               HBM budget the dense cache would take)
    lanes: int = 0                # paged decode batch width; 0 = auto
    # Speculative multi-token decode (serve/speculative.py; paged only).
    # "off" keeps the one-token step; "lookup" = model-free prompt-lookup
    # drafting; "draft" = second-checkpoint draft model (needs
    # spec_draft_root, falls back to lookup without one).
    speculation: str = "off"
    spec_k: int = 4               # max draft tokens per lane per step
    spec_min_accept: float = 0.25  # acceptance EWMA below this -> k=0
    spec_ngram: int = 3           # lookup drafter max n-gram
    spec_probe_every: int = 32    # k=1 probe period for collapsed lanes
    spec_draft_root: str = ""     # draft-model checkpoint root

    def apply_serve_env_overrides(self) -> None:
        """Deployment-property overrides, same contract as the durable
        plane's: OOBLECK_SERVE_PORT, OOBLECK_SERVE_SLOTS,
        OOBLECK_SERVE_RELOAD_SECS, OOBLECK_SERVE_KV_CACHE,
        OOBLECK_SERVE_PAGE_SIZE, OOBLECK_SERVE_KV_PAGES,
        OOBLECK_SERVE_LANES, OOBLECK_SERVE_SPEC, OOBLECK_SERVE_SPEC_K,
        OOBLECK_SERVE_SPEC_MIN_ACCEPT, OOBLECK_SERVE_SPEC_NGRAM,
        OOBLECK_SERVE_SPEC_PROBE_EVERY, OOBLECK_SERVE_SPEC_DRAFT_ROOT
        are settable without editing job yaml."""
        import os

        v = os.environ.get("OOBLECK_SERVE_PORT")
        if v:
            self.port = int(v)
        v = os.environ.get("OOBLECK_SERVE_SLOTS")
        if v:
            self.slots = int(v)
        v = os.environ.get("OOBLECK_SERVE_RELOAD_SECS")
        if v:
            self.reload_secs = float(v)
        v = os.environ.get("OOBLECK_SERVE_KV_CACHE")
        if v:
            self.kv_cache = v
        v = os.environ.get("OOBLECK_SERVE_PAGE_SIZE")
        if v:
            self.page_size = int(v)
        v = os.environ.get("OOBLECK_SERVE_KV_PAGES")
        if v:
            self.kv_pages = int(v)
        v = os.environ.get("OOBLECK_SERVE_LANES")
        if v:
            self.lanes = int(v)
        v = os.environ.get("OOBLECK_SERVE_SPEC")
        if v:
            self.speculation = v
        v = os.environ.get("OOBLECK_SERVE_SPEC_K")
        if v:
            self.spec_k = int(v)
        v = os.environ.get("OOBLECK_SERVE_SPEC_MIN_ACCEPT")
        if v:
            self.spec_min_accept = float(v)
        v = os.environ.get("OOBLECK_SERVE_SPEC_NGRAM")
        if v:
            self.spec_ngram = int(v)
        v = os.environ.get("OOBLECK_SERVE_SPEC_PROBE_EVERY")
        if v:
            self.spec_probe_every = int(v)
        v = os.environ.get("OOBLECK_SERVE_SPEC_DRAFT_ROOT")
        if v:
            self.spec_draft_root = v


@dataclass
class OobleckArguments:
    dist: DistributedArguments = field(default_factory=DistributedArguments)
    job: JobArguments = field(default_factory=JobArguments)
    model: ModelArguments = field(default_factory=ModelArguments)
    execution: ExecutionArguments = field(default_factory=ExecutionArguments)
    serve: ServeArguments = field(default_factory=ServeArguments)

    # ---- plain-dict serialization (wire + yaml) ----

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "OobleckArguments":
        return cls(
            dist=DistributedArguments(**d.get("dist", {})),
            job=JobArguments(**d.get("job", {})),
            model=ModelArguments(**d.get("model", {})),
            execution=ExecutionArguments(**d.get("execution", {})),
            serve=ServeArguments(**d.get("serve", {})),
        )

    @classmethod
    def from_yaml(cls, path: str) -> "OobleckArguments":
        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f) or {})

    def to_yaml(self, path: str) -> None:
        with open(path, "w") as f:
            yaml.safe_dump(self.to_dict(), f, sort_keys=False)
