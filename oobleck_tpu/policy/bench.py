"""Policy-plane microbench: adaptive recovery vs every fixed mechanism.

One scripted churn sequence — a single-host loss followed by a correlated
two-host loss, against a 4-host (8 virtual CPU chips) DP rig with a warm
durable checkpoint — is replayed four times: once with the adaptive
scorer and once with each mechanism forced (``OOBLECK_POLICY``'s three
fixed arms, constructed directly so the arms share one process and one
compile cache). The paper's recovery metric is measured per incident:
failure injection until the NEXT train step completes.

The headline is ``policy_speedup`` = best fixed arm's mean
recovery-to-next-step / adaptive's mean. The acceptance bar is >= 1.0
within noise: the adaptive policy must match the best fixed mechanism on
a churn mix no single fixed arm handles best everywhere (forced reroute
falls back on the correlated loss; forced restore replays lost work on
the easy loss). Decisions per incident ride the output so the comparison
is auditable, not just a mean.

Run as ``python -m oobleck_tpu.policy.bench`` under JAX_PLATFORMS=cpu
with XLA_FLAGS=--xla_force_host_platform_device_count=8 (bench.py and
``make policy-bench`` set this up). Prints ONE JSON line on stdout.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

_MODEL_ARGS = {"hidden_size": 64, "num_layers": 4,
               "max_position_embeddings": 32}

_HOSTS = ["10.0.0.0", "10.0.0.1", "10.0.0.2", "10.0.0.3"]

# The scripted churn: one easy single-host loss (reroute territory), one
# correlated double loss (reroute structurally infeasible).
_INCIDENTS = (["10.0.0.3"], ["10.0.0.1", "10.0.0.2"])


def _make_engine(ckpt_dir: str):
    import jax

    from oobleck_tpu.config import (
        DistributedArguments,
        JobArguments,
        ModelArguments,
        OobleckArguments,
    )
    from oobleck_tpu.execution.engine import OobleckEngine

    args = OobleckArguments(
        dist=DistributedArguments(node_ips=list(_HOSTS)),
        job=JobArguments(
            microbatch_size=1,
            global_microbatch_size=8,
            steps=64,
            learning_rate=1e-3,
            warmup_steps=2,
        ),
        model=ModelArguments(
            model_name="gpt2-tiny", dataset_path="synthetic",
            model_tag="policy-bench",  # own profile cache: non-default args
            model_args=dict(_MODEL_ARGS),
        ),
    )
    args.execution.checkpoint_dir = ckpt_dir
    args.execution.degrade_enabled = True  # the reroute arm needs the plane
    args.execution.precompile_recovery_depth = 0  # mechanism cost, not warmth
    args.execution.eval_fraction = 0.0
    engine = OobleckEngine(args, devices=jax.devices()[:8])
    engine.initialize_distributed()
    engine.instantiate_pipelines(args.job.global_num_microbatch)
    return engine


def _run_arm(mode: str, ckpt_root: str) -> dict:
    """One full churn replay under one policy mode. Fresh engine, fresh
    checkpoint dir, identical incident script."""
    from oobleck_tpu.policy import PolicyEngine
    from oobleck_tpu.utils import metrics

    eng = _make_engine(os.path.join(ckpt_root, mode))
    eng._policy = PolicyEngine(multihost=False, mode=mode)
    for _ in range(2):
        eng._train_step()
    eng.save_checkpoint(wait=True)
    eng._train_step()

    incidents = []
    for lost in _INCIDENTS:
        before = len(metrics.flight_recorder().events())
        t0 = time.perf_counter()
        for ip in lost:
            eng.request_reconfiguration(ip)
        eng._maybe_reconfigure()
        eng._train_step()
        latency = time.perf_counter() - t0
        decision = next(
            (e for e in metrics.flight_recorder().events()[before:]
             if e.get("event") == "policy_decision"), {})
        incidents.append({
            "lost_ips": lost,
            "recovery_to_next_step_s": round(latency, 3),
            "mechanism": decision.get("mechanism"),
            "reason": decision.get("reason"),
            "projected_cost_s": decision.get("projected_cost_s"),
        })
    mean = sum(i["recovery_to_next_step_s"] for i in incidents) / len(
        incidents)
    return {"mean_recovery_to_next_step_s": round(mean, 3),
            "incidents": incidents}


def measure() -> dict:
    out: dict = {
        "rig": "4 hosts x (1-host pipeline on 2 virtual CPU chips), DP "
               "replicas, gpt2-tiny h64/L4/seq32, durable ckpt 1 step old",
        "churn": [",".join(i) for i in _INCIDENTS],
    }
    arms = {}
    with tempfile.TemporaryDirectory(prefix="policy-bench-") as root:
        for mode in ("adaptive", "reroute", "reinstantiate", "restore"):
            arms[mode] = _run_arm(mode, root)
    out["arms"] = arms
    fixed = {m: a["mean_recovery_to_next_step_s"]
             for m, a in arms.items() if m != "adaptive"}
    best_fixed = min(fixed, key=fixed.get)
    adaptive = arms["adaptive"]["mean_recovery_to_next_step_s"]
    out["best_fixed"] = best_fixed
    out["best_fixed_mean_s"] = fixed[best_fixed]
    out["adaptive_mean_s"] = adaptive
    out["policy_speedup"] = (round(fixed[best_fixed] / adaptive, 3)
                             if adaptive > 0 else None)
    # The acceptance bar, self-reported honestly: adaptive within 10%
    # noise of the best fixed arm (it should usually beat it outright —
    # no fixed arm handles both incidents optimally).
    out["adaptive_not_worse"] = bool(
        adaptive <= fixed[best_fixed] * 1.10)
    return out


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
