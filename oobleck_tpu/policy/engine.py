"""PolicyEngine: one auditable, flight-recorded decision per incident.

Consulted at the master's failure-detection point (and by the engine for
in-process losses that never cross the control plane), it gates each
mechanism on feasibility, scores the survivors with the churn-aware cost
model, and returns a PolicyDecision that rides the recovery broadcast —
so every process applies the *same* verdict and the flight recorder can
later compare projected cost against what the recovery actually took.

``OOBLECK_POLICY=reroute|reinstantiate|restore`` forces a fixed arm
(benchmark baselines); the default ``adaptive`` scores. A forced arm
that is infeasible for the incident at hand falls back to
re-instantiation — the one mechanism that is always available.
"""

from __future__ import annotations

import collections
import logging
import os
import time
from dataclasses import dataclass, field

from oobleck_tpu.obs import spans
from oobleck_tpu.policy.health import HostHealthTracker
from oobleck_tpu.policy.scorer import cheapest_feasible, score_arms
from oobleck_tpu.policy.signals import (
    build_arms,
    build_grow_arms,
    build_slowdown_arms,
    priors_provenance,
)
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.policy")

ENV_POLICY = "OOBLECK_POLICY"

MECH_REROUTE = "reroute"
MECH_REINSTANTIATE = "reinstantiate"
MECH_RESTORE = "restore"
# Grow-direction arms (JOIN incidents — capacity ARRIVING, PR 13).
MECH_ABSORB = "absorb_spare"
MECH_GROW_DP = "grow_dp"
MECH_GROW_RESHAPE = "grow_reshape"
# Slowdown-direction arms (SLOWDOWN incidents — gray failures, PR 17).
MECH_OBSERVE = "observe"
MECH_DRAIN = "drain"
MECH_QUARANTINE = "quarantine"
MODE_ADAPTIVE = "adaptive"
GROW_MODES = (MECH_ABSORB, MECH_GROW_DP, MECH_GROW_RESHAPE)
SLOWDOWN_MODES = (MECH_OBSERVE, MECH_DRAIN, MECH_QUARANTINE)
# A forced mode only pins decisions in ITS direction: OOBLECK_POLICY=
# grow_reshape forces grow incidents but leaves loss incidents adaptive
# (and vice versa) — a cross-direction forced arm is not an error, it is
# simply out of scope for that incident.
MODES = (MODE_ADAPTIVE, MECH_REROUTE, MECH_REINSTANTIATE,
         MECH_RESTORE) + GROW_MODES + SLOWDOWN_MODES

# Payload key the recovery broadcast carries the decision under (legacy
# receivers ignore unknown keys, like spans.TRACE_KEY).
DECISION_KEY = "policy"

# Decisions kept for /status (bounded like the master's incident digest).
MAX_DECISIONS = 16
# EWMA weight of the newest measured recovery latency.
EWMA_ALPHA = 0.5


@dataclass
class PolicyDecision:
    """What the policy plane chose for one incident, and what it knew."""

    mechanism: str
    lost_ips: list[str]
    joined_ips: list = field(default_factory=list)  # grow incidents
    reason: str = "cheapest"       # "cheapest" | "forced:<m>" | fallback
    projected_cost_s: float | None = None
    measured_recovery_s: float | None = None
    costs: dict = field(default_factory=dict)       # mechanism -> cost_s
    infeasible: dict = field(default_factory=dict)  # mechanism -> reason
    arms: dict = field(default_factory=dict)        # mechanism -> record
    mtbf_s: float | None = None
    quarantined: list = field(default_factory=list)
    proactive: bool = False        # preemption-notice-triggered
    inplace: bool = False          # multihost survivors reroute in place
    trace_id: str | None = None
    decided_at: float = field(default_factory=time.time)

    def as_payload(self) -> dict:
        """Compact dict that rides the recovery broadcast under
        DECISION_KEY and the /status decision log."""
        return {
            "mechanism": self.mechanism,
            "lost_ips": list(self.lost_ips),
            "joined_ips": list(self.joined_ips),
            "reason": self.reason,
            "projected_cost_s": self.projected_cost_s,
            "measured_recovery_s": self.measured_recovery_s,
            "costs": {m: round(c, 6) for m, c in self.costs.items()},
            "infeasible": dict(self.infeasible),
            "mtbf_s": self.mtbf_s,
            "quarantined": list(self.quarantined),
            "proactive": self.proactive,
            "inplace": self.inplace,
            "trace_id": self.trace_id,
            "decided_at": self.decided_at,
        }

    def as_record(self) -> dict:
        rec = self.as_payload()
        rec["arms"] = dict(self.arms)
        return rec

    def record(self) -> None:
        """Flight-record the decision and bump the oobleck_policy_*
        family in one call, so the two views cannot disagree."""
        metrics.flight_recorder().record("policy_decision",
                                         **self.as_record())
        reg = metrics.registry()
        reg.counter(
            "oobleck_policy_decisions_total",
            "Policy-plane decisions by mechanism and reason",
        ).inc(mechanism=self.mechanism, reason=self.reason)
        if self.projected_cost_s is not None:
            reg.gauge(
                "oobleck_policy_projected_cost_seconds",
                "Projected cost of the last policy decision",
            ).set(self.projected_cost_s, mechanism=self.mechanism)


def decision_from_payload(payload) -> PolicyDecision | None:
    """Rebuild a broadcast decision on the receiving side; tolerant of
    legacy peers (no payload) and future extra keys."""
    if not isinstance(payload, dict) or "mechanism" not in payload:
        return None
    d = PolicyDecision(mechanism=str(payload["mechanism"]),
                       lost_ips=list(payload.get("lost_ips") or []))
    for k in ("joined_ips", "reason", "projected_cost_s", "costs",
              "infeasible", "mtbf_s", "quarantined", "proactive", "inplace",
              "trace_id", "decided_at"):
        if k in payload and payload[k] is not None:
            setattr(d, k, payload[k])
    return d


class PolicyEngine:
    """Per-process policy state: mode, host health, latency EWMAs, and the
    bounded decision log surfaced in /status."""

    def __init__(self, *, multihost: bool = False, clock=time.monotonic,
                 mode: str | None = None, registry=None,
                 priors_path: str | None = None):
        if mode is None:
            mode = os.environ.get(ENV_POLICY, "").strip().lower()
        self.mode = mode or MODE_ADAPTIVE
        if self.mode not in MODES:
            raise ValueError(
                f"bad {ENV_POLICY}={self.mode!r}: want one of {MODES}")
        self.multihost = multihost
        self.health = HostHealthTracker(clock=clock)
        # Injectable metrics registry (like the clock): the cluster
        # simulator runs each scenario on a fresh Registry so measured
        # history from one run can never leak into the next; production
        # callers keep the process-global default.
        self._registry = registry
        self._priors_path = priors_path
        self._ewma: dict[str, float] = {}
        self._decisions: collections.deque = collections.deque(
            maxlen=MAX_DECISIONS)

    # -- signal feeds ------------------------------------------------------- #

    def observe_failure(self, ip: str, cause: str = "") -> None:
        self.health.record_failure(ip, cause)
        reg = self._registry or metrics.registry()
        reg.gauge(
            "oobleck_policy_quarantined_hosts",
            "Hosts currently quarantined by the flap detector",
        ).set(len(self.health.quarantined()))

    def observe_measured(self, mechanism: str, seconds: float) -> None:
        """Feed one measured recovery latency: updates the EWMA the next
        decision scores with, and closes the projected-vs-measured loop on
        the latest matching decision."""
        prev = self._ewma.get(mechanism)
        self._ewma[mechanism] = (seconds if prev is None else
                                 (1 - EWMA_ALPHA) * prev
                                 + EWMA_ALPHA * seconds)
        reg = self._registry or metrics.registry()
        reg.histogram(
            "oobleck_policy_measured_recovery_seconds",
            "Measured recovery latency by mechanism (policy feedback)",
        ).observe(seconds, mechanism=mechanism)
        for d in reversed(self._decisions):
            if d.mechanism == mechanism and d.measured_recovery_s is None:
                d.measured_recovery_s = seconds
                metrics.flight_recorder().record(
                    "policy_decision_measured", mechanism=mechanism,
                    trace_id=d.trace_id,
                    projected_cost_s=d.projected_cost_s,
                    measured_recovery_s=seconds)
                break

    def is_quarantined(self, ip: str) -> bool:
        return self.health.is_quarantined(ip)

    # -- journal persistence ------------------------------------------------- #

    def ewma_snapshot(self) -> dict[str, float]:
        """The measured-latency EWMAs, for the master's durable journal —
        the adaptive state a restarted master must not re-learn from
        scratch (every decision before the first post-restart measurement
        would otherwise score on cold priors)."""
        return dict(self._ewma)

    def restore_persisted(self, state: dict, *,
                          wall_now: float | None = None) -> None:
        """Rehydrate journal-persisted adaptive state after a master
        restart: latency EWMAs verbatim, per-host failure logs and
        quarantine entries via the health tracker's clock-domain
        conversion. Decisions are NOT restored — a decision log from a
        dead incarnation describes incidents that incarnation closed."""
        for m, v in (state.get("ewma") or {}).items():
            try:
                self._ewma[str(m)] = float(v)
            except (TypeError, ValueError):
                continue
        self.health.restore(
            failures=state.get("failures") or {},
            causes=state.get("causes") or {},
            quarantined=state.get("quarantined") or {},
            wall_now=wall_now,
        )

    # -- the decision ------------------------------------------------------- #

    def decide(self, lost_ips: list[str], *,
               degrade_enabled: bool = True,
               reroute_retention: float | None = None,
               reroute_feasible: bool = True,
               reroute_reason: str = "",
               survivor_frac: float = 1.0,
               staleness_steps: float | None = None,
               step_seconds: float | None = None,
               proactive: bool = False,
               cause: str = "") -> PolicyDecision:
        """Score the arms for one incident and pick. ``lost_ips`` with more
        than one entry is a correlated failure (reroute infeasible).
        ``staleness_steps`` None means no durable checkpoint."""
        with spans.span("policy.decide", lost_ips=",".join(lost_ips),
                        cause=cause) as ctx:
            arms = build_arms(
                multihost=self.multihost,
                degrade_enabled=degrade_enabled,
                correlated=len(lost_ips) > 1,
                reroute_retention=reroute_retention,
                reroute_feasible=reroute_feasible,
                reroute_reason=reroute_reason,
                survivor_frac=survivor_frac,
                staleness_steps=staleness_steps,
                step_seconds=step_seconds,
                latency_overrides=self._ewma,
                registry=self._registry,
                priors_path=self._priors_path,
            )
            mtbfs = [m for m in (self.health.mtbf(ip) for ip in lost_ips)
                     if m is not None]
            mtbf_s = min(mtbfs) if mtbfs else self.health.fleet_mtbf()
            scored = score_arms(arms, mtbf_s=mtbf_s)

            # A forced GROW arm is out of scope for a loss incident: this
            # decision scores adaptively (the forced arm keeps pinning
            # decide_grow).
            forced = self.mode if self.mode in scored else MODE_ADAPTIVE
            if forced != MODE_ADAPTIVE:
                if scored[forced].feasible:
                    chosen, reason = scored[forced], f"forced:{forced}"
                else:
                    chosen = scored[MECH_REINSTANTIATE]
                    reason = (f"forced:{forced}:infeasible:"
                              f"{scored[forced].reason}")
            else:
                chosen = cheapest_feasible(scored)
                reason = "cheapest"
                if chosen is None:  # cannot happen: reinstantiate is
                    chosen = scored[MECH_REINSTANTIATE]  # always feasible
                    reason = "fallback"

            decision = PolicyDecision(
                mechanism=chosen.mechanism,
                lost_ips=list(lost_ips),
                reason=reason,
                projected_cost_s=chosen.cost_s,
                costs={m: a.cost_s for m, a in scored.items()},
                infeasible={m: a.reason for m, a in scored.items()
                            if not a.feasible},
                arms={m: dict(arms[m].as_record(),
                              **scored[m].as_record())
                      for m in arms},
                mtbf_s=mtbf_s,
                quarantined=self.health.quarantined(),
                proactive=proactive,
                inplace=(chosen.mechanism == MECH_REROUTE
                         and (not self.multihost or proactive)),
                trace_id=ctx["trace_id"],
            )
        logger.info(
            "policy: %s for loss of %s (reason=%s cost=%.3fs mtbf=%s)",
            decision.mechanism, lost_ips, reason, chosen.cost_s,
            f"{mtbf_s:.1f}s" if mtbf_s is not None else "n/a")
        self._decisions.append(decision)
        decision.record()
        return decision

    def decide_grow(self, joined_ips: list[str], *,
                    current_hosts: int,
                    dp_feasible: bool = True,
                    dp_reason: str = "",
                    staleness_steps: float | None = None,
                    step_seconds: float | None = None,
                    lifetime_hints: dict[str, float] | None = None,
                    cause: str = "join") -> PolicyDecision:
        """Score the grow arms for one JOIN incident and pick.

        The amortization horizon is the arriving capacity's expected
        LIFETIME, not the fleet's failure cadence: a `lifetime_hints`
        entry (spot metadata / chaos spot_lifetime) wins, then the
        joining host's own online MTBF (a flapper that left and came
        back carries its history), then the fleet MTBF. Short expected
        lifetimes make absorb_spare cheap — there is nothing to amortize
        a reshape against — and simultaneously raise the churn hedge on
        the arms that commit state to the newcomer."""
        hints = lifetime_hints or {}
        with spans.span("policy.decide_grow",
                        joined_ips=",".join(joined_ips), cause=cause) as ctx:
            arms = build_grow_arms(
                joined_count=len(joined_ips),
                current_hosts=current_hosts,
                dp_feasible=dp_feasible,
                dp_reason=dp_reason,
                staleness_steps=staleness_steps,
                step_seconds=step_seconds,
                latency_overrides=self._ewma,
                registry=self._registry,
                priors_path=self._priors_path,
            )
            lifetimes = [
                lt for lt in (hints.get(ip) or self.health.mtbf(ip)
                              for ip in joined_ips)
                if lt is not None
            ]
            mtbf_s = min(lifetimes) if lifetimes else self.health.fleet_mtbf()
            scored = score_arms(arms, mtbf_s=mtbf_s)

            # A forced SHRINK arm is out of scope here (see MODES); an
            # infeasible forced grow arm falls back to absorb_spare — the
            # grow direction's always-available mechanism.
            forced = self.mode if self.mode in scored else MODE_ADAPTIVE
            if forced != MODE_ADAPTIVE:
                if scored[forced].feasible:
                    chosen, reason = scored[forced], f"forced:{forced}"
                else:
                    chosen = scored[MECH_ABSORB]
                    reason = (f"forced:{forced}:infeasible:"
                              f"{scored[forced].reason}")
            else:
                chosen = cheapest_feasible(scored)
                reason = "cheapest"
                if chosen is None:  # cannot happen: absorb_spare is
                    chosen = scored[MECH_ABSORB]  # always feasible
                    reason = "fallback"

            decision = PolicyDecision(
                mechanism=chosen.mechanism,
                lost_ips=[],
                joined_ips=list(joined_ips),
                reason=reason,
                projected_cost_s=chosen.cost_s,
                costs={m: a.cost_s for m, a in scored.items()},
                infeasible={m: a.reason for m, a in scored.items()
                            if not a.feasible},
                arms={m: dict(arms[m].as_record(),
                              **scored[m].as_record())
                      for m in arms},
                mtbf_s=mtbf_s,
                quarantined=self.health.quarantined(),
                trace_id=ctx["trace_id"],
            )
        logger.info(
            "policy: %s for join of %s (reason=%s cost=%.3fs lifetime=%s)",
            decision.mechanism, joined_ips, reason, chosen.cost_s,
            f"{mtbf_s:.1f}s" if mtbf_s is not None else "n/a")
        self._decisions.append(decision)
        decision.record()
        return decision

    def decide_slowdown(self, slow_ip: str, *,
                        slowdown_ratio: float,
                        survivor_frac: float = 1.0,
                        cause: str = "slowdown") -> PolicyDecision:
        """Score the SLOWDOWN arms for one gray-failure incident and pick.

        ``slowdown_ratio`` is the straggler's step time over the fleet
        median (the fleet tracker's judgment); ``survivor_frac`` what the
        fleet keeps after draining the host. The risk horizon is the SICK
        host's own MTBF when it has one — a host that has been failing is
        priced as about to fail again, which is what drains it before it
        dies. The chosen drain/quarantine decision is marked proactive +
        inplace: the victim's worker is still ALIVE and flushes a clean
        checkpoint on the way out (the preemption-notice drain path),
        while multihost survivors reroute in place with zero respawns."""
        with spans.span("policy.decide_slowdown", lost_ips=slow_ip,
                        cause=cause) as ctx:
            host_mtbf = self.health.mtbf(slow_ip)
            arms = build_slowdown_arms(
                slowdown_ratio=slowdown_ratio,
                survivor_frac=survivor_frac,
                host_mtbf_s=host_mtbf,
                host_failures=self.health.failure_count(slow_ip),
                latency_overrides=self._ewma,
                registry=self._registry,
                priors_path=self._priors_path,
            )
            mtbf_s = host_mtbf if host_mtbf is not None \
                else self.health.fleet_mtbf()
            scored = score_arms(arms, mtbf_s=mtbf_s)

            # A forced loss/grow arm is out of scope for a slowdown (see
            # MODES); an infeasible forced slowdown arm falls back to
            # observe — the direction's always-available mechanism.
            forced = self.mode if self.mode in scored else MODE_ADAPTIVE
            if forced != MODE_ADAPTIVE:
                if scored[forced].feasible:
                    chosen, reason = scored[forced], f"forced:{forced}"
                else:
                    chosen = scored[MECH_OBSERVE]
                    reason = (f"forced:{forced}:infeasible:"
                              f"{scored[forced].reason}")
            else:
                chosen = cheapest_feasible(scored)
                reason = "cheapest"
                if chosen is None:  # cannot happen: observe is
                    chosen = scored[MECH_OBSERVE]  # always feasible
                    reason = "fallback"

            if chosen.mechanism == MECH_QUARANTINE:
                self.health.quarantine(slow_ip, cause=cause)

            active = chosen.mechanism in (MECH_DRAIN, MECH_QUARANTINE)
            decision = PolicyDecision(
                mechanism=chosen.mechanism,
                lost_ips=[slow_ip],
                reason=reason,
                projected_cost_s=chosen.cost_s,
                costs={m: a.cost_s for m, a in scored.items()},
                infeasible={m: a.reason for m, a in scored.items()
                            if not a.feasible},
                arms={m: dict(arms[m].as_record(),
                              **scored[m].as_record())
                      for m in arms},
                mtbf_s=mtbf_s,
                quarantined=self.health.quarantined(),
                proactive=active,
                inplace=active and self.multihost,
                trace_id=ctx["trace_id"],
            )
        logger.info(
            "policy: %s for slowdown of %s (ratio=%.2f reason=%s "
            "cost=%.3fs mtbf=%s)",
            decision.mechanism, slow_ip, slowdown_ratio, reason,
            chosen.cost_s,
            f"{mtbf_s:.1f}s" if mtbf_s is not None else "n/a")
        self._decisions.append(decision)
        decision.record()
        return decision

    # -- /status ------------------------------------------------------------ #

    def status(self) -> dict:
        """Bounded policy block for the master's /status."""
        health = self.health.snapshot()
        return {
            "mode": self.mode,
            "priors": priors_provenance(self._priors_path),
            "quarantined": health["quarantined"],
            "hosts": health["hosts"],
            "latency_ewma_s": {m: round(v, 6)
                               for m, v in self._ewma.items()},
            "decisions": [d.as_payload() for d in self._decisions],
        }
