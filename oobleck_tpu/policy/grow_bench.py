"""Grow-plane microbench: join-to-first-post-grow-step for each grow arm.

One scripted arrival — two fresh hosts joining a 2-host (4 virtual CPU
chips) rig mid-training, with a warm durable checkpoint — is replayed
four times: once per forced grow arm (``absorb_spare`` / ``grow_dp`` /
``grow_reshape``, constructed directly so the arms share one process and
one compile cache) and once adaptive. The paper's recovery metric is
measured in the grow direction: JOIN injection until the NEXT train step
completes, plus the step time before and after the grow so the output
shows whether the arm actually bought throughput (absorb_spare by design
does not; grow_dp and grow_reshape must — the arrivals double the fleet).

Run as ``python -m oobleck_tpu.policy.grow_bench`` under
JAX_PLATFORMS=cpu with XLA_FLAGS=--xla_force_host_platform_device_count=8
(bench.py and ``make grow-bench`` set this up): the engine binds the
first 4 virtual devices, the joiners bind the free 4. Prints ONE JSON
line on stdout.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

_MODEL_ARGS = {"hidden_size": 64, "num_layers": 4,
               "max_position_embeddings": 32}

_HOSTS = ["10.0.0.0", "10.0.0.1"]
_JOINERS = ["10.0.0.2", "10.0.0.3"]

ARMS = ("adaptive", "absorb_spare", "grow_dp", "grow_reshape")


def _make_engine(ckpt_dir: str):
    import jax

    from oobleck_tpu.config import (
        DistributedArguments,
        JobArguments,
        ModelArguments,
        OobleckArguments,
    )
    from oobleck_tpu.execution.engine import OobleckEngine

    args = OobleckArguments(
        dist=DistributedArguments(node_ips=list(_HOSTS)),
        job=JobArguments(
            microbatch_size=1,
            global_microbatch_size=8,
            steps=64,
            learning_rate=1e-3,
            warmup_steps=2,
        ),
        model=ModelArguments(
            model_name="gpt2-tiny", dataset_path="synthetic",
            model_tag="grow-bench",  # own profile cache: non-default args
            model_args=dict(_MODEL_ARGS),
        ),
    )
    args.execution.checkpoint_dir = ckpt_dir
    args.execution.precompile_recovery_depth = 0  # mechanism cost, not warmth
    args.execution.eval_fraction = 0.0
    engine = OobleckEngine(args, devices=jax.devices()[:4])
    engine.initialize_distributed()
    engine.instantiate_pipelines(args.job.global_num_microbatch)
    return engine


def _timed_step(eng) -> float:
    t0 = time.perf_counter()
    eng._train_step()
    return time.perf_counter() - t0


def _run_arm(mode: str, ckpt_root: str) -> dict:
    """One scripted arrival under one policy mode. Fresh engine, fresh
    checkpoint dir, identical joiners."""
    from oobleck_tpu.policy import PolicyEngine
    from oobleck_tpu.utils import metrics

    eng = _make_engine(os.path.join(ckpt_root, mode))
    eng._policy = PolicyEngine(multihost=False, mode=mode)
    for _ in range(2):
        eng._train_step()
    eng.save_checkpoint(wait=True)
    step_before = _timed_step(eng)

    before = len(metrics.flight_recorder().events())
    t0 = time.perf_counter()
    eng.request_grow(list(_JOINERS))
    eng._maybe_grow()
    eng._train_step()
    latency = time.perf_counter() - t0
    step_after = _timed_step(eng)

    tail = metrics.flight_recorder().events()[before:]
    decision = next((e for e in tail
                     if e.get("event") == "policy_decision"), {})
    return {
        "join_to_first_step_s": round(latency, 3),
        "step_s_before": round(step_before, 3),
        "step_s_after": round(step_after, 3),
        "mechanism": decision.get("mechanism"),
        "reason": decision.get("reason"),
        "projected_cost_s": decision.get("projected_cost_s"),
        "hosts_after": len(eng.host_ips),
        "spares_after": len(eng._spare_hosts),
        "pipelines_after": len(eng.pipelines),
    }


def measure() -> dict:
    out: dict = {
        "rig": "2 hosts x (1-host pipeline on 2 virtual CPU chips) growing "
               "by 2 joiners, gpt2-tiny h64/L4/seq32, durable ckpt warm",
        "joiners": list(_JOINERS),
    }
    arms = {}
    with tempfile.TemporaryDirectory(prefix="grow-bench-") as root:
        for mode in ARMS:
            arms[mode] = _run_arm(mode, root)
    out["arms"] = arms
    # Headline per direction of the tradeoff: the cheapest interruption
    # (absorb) and the cheapest arm that actually grew throughput.
    out["absorb_join_s"] = arms["absorb_spare"]["join_to_first_step_s"]
    grew = {m: a for m, a in arms.items()
            if m in ("grow_dp", "grow_reshape")
            and a["pipelines_after"] > 2}
    if grew:
        best = min(sorted(grew), key=lambda m: grew[m]["join_to_first_step_s"])
        out["best_grow_arm"] = best
        out["best_grow_join_s"] = grew[best]["join_to_first_step_s"]
    return out


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
