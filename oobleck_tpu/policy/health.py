"""Online per-host MTBF estimation + flap quarantine with hysteresis.

A host that fails once is unlucky; a host that fails twice inside its own
mean-time-between-failures window is flapping, and readmitting it to the
next re-plan just schedules the next incident. The tracker quarantines
such hosts and only lifts the quarantine after the host has stayed quiet
for ``hysteresis_factor`` times its window — the asymmetry (quick to
quarantine, slow to forgive) is the hysteresis that stops a
2-second-period flapper from oscillating in and out of the plan.

The clock is injectable so quarantine enter/exit is unit-testable
without sleeping; all other consumers use the monotonic default.
"""

from __future__ import annotations

import time

# A host's first failure gives no interval to estimate MTBF from; until a
# second one lands, "twice within its MTBF window" is judged against this
# default window instead.
DEFAULT_WINDOW_S = 300.0
# Quarantine lifts only after hysteresis_factor * window of silence.
HYSTERESIS_FACTOR = 2.0
# Failure timestamps kept per host (MTBF over at most this many events).
MAX_EVENTS_PER_HOST = 32


class HostHealthTracker:
    """Failure-log-fed MTBF estimates and a quarantine set for the policy
    engine. Not thread-safe by itself — callers (the master's single event
    loop, the engine's reconfigure lock) already serialize access."""

    def __init__(self, clock=time.monotonic, *,
                 default_window_s: float = DEFAULT_WINDOW_S,
                 hysteresis_factor: float = HYSTERESIS_FACTOR):
        self._clock = clock
        self._default_window_s = default_window_s
        self._hysteresis_factor = hysteresis_factor
        self._failures: dict[str, list[float]] = {}
        self._causes: dict[str, str] = {}
        self._quarantined_at: dict[str, float] = {}
        # Hosts whose quarantine lifted (hysteresis satisfied) and that
        # have not yet re-registered: the master's REGISTER path consumes
        # this to tag the handshake as a quarantine_rejoin rather than a
        # first-contact register.
        self._lifted: set[str] = set()

    # -- failure log -------------------------------------------------------- #

    def record_failure(self, ip: str, cause: str = "") -> None:
        """Feed one observed failure; may enter quarantine (two failures
        within the host's window)."""
        now = self._clock()
        log = self._failures.setdefault(ip, [])
        window = self.window(ip)
        if log and now - log[-1] <= window:
            self._quarantined_at[ip] = now
            self._lifted.discard(ip)  # relapse voids any pending rejoin tag
        log.append(now)
        del log[:-MAX_EVENTS_PER_HOST]
        if cause:
            self._causes[ip] = cause

    def failure_count(self, ip: str) -> int:
        return len(self._failures.get(ip, ()))

    def quarantine(self, ip: str, cause: str = "") -> None:
        """Force a host into quarantine NOW (the policy plane's explicit
        quarantine arm — a gray-failing host barred from readmission
        without waiting for the two-failures-in-window rule). The event
        counts as an observed health incident, so the usual hysteresis
        lift (quiet for hysteresis_factor * window) applies from here."""
        now = self._clock()
        log = self._failures.setdefault(ip, [])
        log.append(now)
        del log[:-MAX_EVENTS_PER_HOST]
        self._quarantined_at[ip] = now
        self._lifted.discard(ip)
        if cause:
            self._causes[ip] = cause

    # -- MTBF --------------------------------------------------------------- #

    def mtbf(self, ip: str) -> float | None:
        """Mean seconds between this host's observed failures; None until
        two failures give a first interval."""
        log = self._failures.get(ip, ())
        if len(log) < 2:
            return None
        return (log[-1] - log[0]) / (len(log) - 1)

    def window(self, ip: str) -> float:
        """The "failed twice within" judgment window for this host."""
        return self.mtbf(ip) or self._default_window_s

    def fleet_mtbf(self) -> float | None:
        """Shortest per-host MTBF across the fleet — the churn-storm signal
        the scorer's risk term keys on (the next failure comes from the
        worst host, not the average one)."""
        vals = [m for m in (self.mtbf(ip) for ip in self._failures)
                if m is not None]
        return min(vals) if vals else None

    # -- quarantine --------------------------------------------------------- #

    def is_quarantined(self, ip: str) -> bool:
        """Whether this host is currently excluded from re-plans. Lifts
        lazily once the host has stayed quiet for hysteresis_factor * its
        window (proven stable)."""
        entered = self._quarantined_at.get(ip)
        if entered is None:
            return False
        last = self._failures[ip][-1]
        if self._clock() - last >= self._hysteresis_factor * self.window(ip):
            del self._quarantined_at[ip]
            self._lifted.add(ip)
            return False
        return True

    def consume_lift(self, ip: str) -> bool:
        """One-shot: True iff this host's quarantine lifted since it last
        (re)registered — the REGISTER handshake for such a host is a
        quarantine REJOIN, and the distinction must survive into the
        flight record. Calling is_quarantined first ensures a lazily
        expired quarantine is counted before being consumed."""
        self.is_quarantined(ip)
        if ip in self._lifted:
            self._lifted.discard(ip)
            return True
        return False

    def quarantined(self) -> list[str]:
        return sorted(ip for ip in list(self._quarantined_at)
                      if self.is_quarantined(ip))

    # -- journal restore ----------------------------------------------------- #

    def restore(self, *, failures: dict[str, list[float]],
                causes: dict[str, str] | None = None,
                quarantined: dict[str, float] | None = None,
                wall_now: float | None = None) -> None:
        """Rehydrate journaled state after a master restart.

        Journal timestamps are wall-clock (monotonic clocks do not survive
        a process restart); each is converted into this tracker's clock
        domain by age — an event `wall_now - ts` seconds old lands
        `clock() - age` on the injected clock, so MTBF intervals and the
        quarantine hysteresis keep their real-world meaning across the
        restart. Quarantined entries without a failure log are dropped
        (is_quarantined reads the last failure to judge the lift)."""
        if wall_now is None:
            wall_now = time.time()
        now_clock = self._clock()

        def conv(ts: float) -> float:
            return now_clock - max(wall_now - float(ts), 0.0)

        self._failures = {
            ip: sorted(conv(t) for t in log)[-MAX_EVENTS_PER_HOST:]
            for ip, log in failures.items() if log
        }
        self._causes = dict(causes or {})
        self._quarantined_at = {
            ip: conv(t) for ip, t in (quarantined or {}).items()
            if ip in self._failures
        }
        self._lifted = set()

    # -- /status ------------------------------------------------------------ #

    def snapshot(self) -> dict:
        """Bounded per-host view for the master's /status policy block."""
        hosts = {}
        for ip, log in self._failures.items():
            hosts[ip] = {
                "failures": len(log),
                "mtbf_s": self.mtbf(ip),
                "last_failure_age_s": round(self._clock() - log[-1], 3),
                "quarantined": self.is_quarantined(ip),
            }
            if ip in self._causes:
                hosts[ip]["last_cause"] = self._causes[ip]
        return {"hosts": hosts, "quarantined": self.quarantined()}
