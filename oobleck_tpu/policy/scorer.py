"""Cost model: seconds-of-training-lost per mechanism, per incident.

    cost(m) = L(m)                      recovery latency
            + W(m)                      replayed work (restore staleness)
            + (1 - retention(m)) * T    degraded throughput, amortized
                                        until the next reconfiguration
                                        opportunity (T = min(MTBF, cap))
            + risk * (L(restore) + W(restore))   in-memory arms only

The risk term is the churn hedge: under a churn storm (MTBF shorter than
the risk horizon) every in-memory recovery just schedules the next one,
and the cascade ends in a checkpoint restore anyway — at *worse*
staleness than restoring now, while the checkpoint is fresh. risk =
clamp(horizon / MTBF, 0, 1) prices that in: a host failing every few
seconds drives risk to 1 and the scorer to restore; rising MTBF decays
the term and flips the choice back to the cheap in-memory arms. With no
failure history at all (first incident) risk is 0 and T falls back to
the cap — the scorer then reduces to "cheapest latency at equal
retention", which is the reroute-first behavior the fixed policy had.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from oobleck_tpu.policy.signals import ArmSignals, PRIOR_LATENCY_S

# Amortization horizon cap: past this, degraded throughput is assumed to
# be fixed by a scheduled re-plan / checkpoint cycle anyway.
AMORT_CAP_S = 300.0
# Churn risk saturates when MTBF drops below this horizon.
RISK_HORIZON_S = 60.0


@dataclass
class ScoredArm:
    mechanism: str
    cost_s: float
    feasible: bool
    reason: str = ""
    breakdown: dict = field(default_factory=dict)

    def as_record(self) -> dict:
        return {
            "cost_s": round(self.cost_s, 6),
            "feasible": self.feasible,
            "reason": self.reason,
            "breakdown": {k: round(v, 6) for k, v in self.breakdown.items()},
        }


def score_arms(arms: dict[str, ArmSignals], *,
               mtbf_s: float | None = None,
               amort_cap_s: float = AMORT_CAP_S,
               risk_horizon_s: float = RISK_HORIZON_S
               ) -> dict[str, ScoredArm]:
    """Score every arm (including infeasible ones, so decisions record
    what the road not taken would have cost)."""
    if mtbf_s is not None and mtbf_s > 0:
        t_amort = min(mtbf_s, amort_cap_s)
        risk = min(max(risk_horizon_s / mtbf_s, 0.0), 1.0)
    else:
        t_amort = amort_cap_s
        risk = 0.0

    restore = arms.get("restore")
    if restore is not None:
        restore_total = restore.latency_s + restore.lost_work_s
    else:
        restore_total = PRIOR_LATENCY_S["restore"]

    scored: dict[str, ScoredArm] = {}
    for name, arm in arms.items():
        latency = arm.latency_s
        lost_work = arm.lost_work_s
        degraded = (1.0 - min(arm.retention, 1.0)) * t_amort
        churn = risk * restore_total if arm.in_memory else 0.0
        # Cross-tenant terms (zero on single-tenant arms): SLO debt the
        # pressured tenant keeps paying under arms that don't relieve it,
        # and the preemption cost charged to a tenant whose running
        # capacity an arm takes away (pool/arbiter.py).
        slo_debt = max(arm.slo_debt_s, 0.0)
        preempt = max(arm.preempt_cost_s, 0.0)
        scored[name] = ScoredArm(
            mechanism=name,
            cost_s=latency + lost_work + degraded + churn + slo_debt + preempt,
            feasible=arm.feasible,
            reason=arm.reason,
            breakdown={
                "latency_s": latency,
                "lost_work_s": lost_work,
                "degraded_s": degraded,
                "churn_risk_s": churn,
                "slo_debt_s": slo_debt,
                "preempt_cost_s": preempt,
                "t_amort_s": t_amort,
                "risk": risk,
            },
        )
    return scored


def cheapest_feasible(scored: dict[str, ScoredArm]) -> ScoredArm | None:
    """The cheapest feasible arm, ties broken by (cost, mechanism name)
    for determinism; None if nothing is feasible."""
    candidates = sorted(
        (a for a in scored.values() if a.feasible),
        key=lambda a: (a.cost_s, a.mechanism))
    return candidates[0] if candidates else None
