"""Adaptive fault-tolerance policy plane (ROADMAP item 2).

The repo has three independent recovery mechanisms — bubble rerouting
(degrade/, ~0.6 s), template re-instantiation (~0.7 s warm in-place /
~21 s respawn), and checkpoint restore (ckpt/) — and until this package
the choice between them was a single env var. The policy engine scores
each *feasible* mechanism per incident from live signals (measured
recovery-latency history, the degrade planner's projected survivor
slowdown, checkpoint staleness, an online per-host MTBF estimator) and
picks the cheapest, so the cluster self-tunes under churn instead of
replaying one fixed reflex.

Chameleon-style real-time policy selection (PAPERS.md, arxiv 2508.21613)
layered over ReCycle-style pipeline adaptation (arxiv 2405.14009).

``OOBLECK_POLICY`` forces a fixed arm (``reroute`` | ``reinstantiate`` |
``restore``) for baselines/benchmarks; the default ``adaptive`` scores.
"""

from oobleck_tpu.policy.engine import (  # noqa: F401
    DECISION_KEY,
    ENV_POLICY,
    GROW_MODES,
    MECH_ABSORB,
    MECH_GROW_DP,
    MECH_GROW_RESHAPE,
    MECH_REINSTANTIATE,
    MECH_REROUTE,
    MECH_RESTORE,
    MODE_ADAPTIVE,
    PolicyDecision,
    PolicyEngine,
    decision_from_payload,
)
from oobleck_tpu.policy.health import HostHealthTracker  # noqa: F401
from oobleck_tpu.policy.scorer import score_arms  # noqa: F401
from oobleck_tpu.policy.signals import (  # noqa: F401
    ArmSignals,
    build_arms,
    build_grow_arms,
)
