"""Live per-mechanism signals the scorer consumes.

Each recovery mechanism becomes one ArmSignals record: its expected
recovery latency (measured history when the metrics plane has any,
documented priors otherwise — the source is carried so decisions are
honest about what they knew), its projected post-recovery throughput
retention, the work a checkpoint restore would replay, and feasibility
(a reroute around two correlated losses, or a restore with no durable
checkpoint, is not an option however cheap it looks).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from oobleck_tpu.utils import metrics

# Latency priors (seconds) used until a mechanism has measured history.
# reroute/reinstantiate-warm come from the degrade bench (~0.56 s / ~0.64 s
# on the reference shape, rounded up); reinstantiate-respawn and restore
# from the multiprocess recovery runs (~21 s respawn; restore adds durable
# read + re-instantiation on top).
PRIOR_LATENCY_S = {
    "reroute": 0.6,
    "reinstantiate": 0.7,          # warm in-place re-instantiation
    "reinstantiate_respawn": 21.0,  # multihost: respawn + re-init
    "restore": 25.0,
}
# Step-time prior when no measured step seconds are available yet (only
# used to price checkpoint staleness in lost-work seconds).
PRIOR_STEP_S = 1.0

# Histogram families that hold measured recovery latencies by mechanism.
_LATENCY_HISTOGRAMS = (
    "oobleck_degrade_recovery_seconds",
    "oobleck_policy_measured_recovery_seconds",
)


@dataclass
class ArmSignals:
    """Everything the scorer needs to know about one recovery mechanism
    for one incident."""

    mechanism: str
    latency_s: float
    latency_source: str            # "measured" | "prior"
    retention: float               # projected throughput after recovery
    lost_work_s: float = 0.0       # replayed work (checkpoint restore)
    in_memory: bool = True         # state survives in RAM -> churn risk
    feasible: bool = True
    reason: str = ""               # why infeasible ("" when feasible)

    def as_record(self) -> dict:
        return {
            "latency_s": round(self.latency_s, 6),
            "latency_source": self.latency_source,
            "retention": round(self.retention, 6),
            "lost_work_s": round(self.lost_work_s, 6),
            "feasible": self.feasible,
            "reason": self.reason,
        }


def measured_latency(mechanism: str, registry=None) -> float | None:
    """Mean measured recovery latency for a mechanism across the metric
    families that observe it, or None with no history."""
    reg = registry or metrics.registry()
    total = count = 0.0
    for name in _LATENCY_HISTOGRAMS:
        # Reads families registered (literally) elsewhere; the loop
        # variable is what makes the name dynamic here.
        # oobleck: allow[OBL005] -- iterates the registered name list
        for s in reg.histogram(name, "").series():
            if s["labels"].get("mechanism") == mechanism and s["count"]:
                total += s["sum"]
                count += s["count"]
    return total / count if count else None


def _latency(mechanism: str, prior_key: str, overrides, registry):
    if overrides and mechanism in overrides:
        return float(overrides[mechanism]), "measured"
    m = measured_latency(mechanism, registry)
    if m is not None:
        return m, "measured"
    return PRIOR_LATENCY_S[prior_key], "prior"


def build_arms(*,
               multihost: bool = False,
               warm_reinstantiate: bool | None = None,
               degrade_enabled: bool = True,
               correlated: bool = False,
               reroute_retention: float | None = None,
               reroute_feasible: bool = True,
               reroute_reason: str = "",
               survivor_frac: float = 1.0,
               staleness_steps: float | None = None,
               step_seconds: float | None = None,
               latency_overrides: dict[str, float] | None = None,
               registry=None) -> dict[str, ArmSignals]:
    """Assemble the three arms for one incident.

    staleness_steps is None when there is no durable checkpoint (restore
    infeasible), else current_step - last_durable_step. reroute_retention
    is the degrade planner's replay-projected survivor throughput when a
    projection exists; survivor_frac ((n-lost)/n) is the fallback for it
    and the default for the other in-memory arm — re-instantiated
    templates run on the same survivors, so absent measurements the arms
    are not fabricated apart on retention.
    """
    if warm_reinstantiate is None:
        warm_reinstantiate = not multihost

    reroute = ArmSignals(
        mechanism="reroute",
        latency_s=0.0, latency_source="",
        retention=(reroute_retention if reroute_retention is not None
                   else survivor_frac),
    )
    reroute.latency_s, reroute.latency_source = _latency(
        "reroute", "reroute", latency_overrides, registry)
    if not degrade_enabled:
        reroute.feasible, reroute.reason = False, "degrade_disabled"
    elif correlated:
        reroute.feasible, reroute.reason = False, "correlated_failure"
    elif not reroute_feasible:
        reroute.feasible, reroute.reason = False, (reroute_reason
                                                   or "reroute_infeasible")
    reinst = ArmSignals(
        mechanism="reinstantiate",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
    )
    reinst.latency_s, reinst.latency_source = _latency(
        "reinstantiate",
        "reinstantiate" if warm_reinstantiate else "reinstantiate_respawn",
        latency_overrides, registry)

    restore = ArmSignals(
        mechanism="restore",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
        in_memory=False,
    )
    restore.latency_s, restore.latency_source = _latency(
        "restore", "restore", latency_overrides, registry)
    if staleness_steps is None:
        restore.feasible, restore.reason = False, "no_durable_checkpoint"
    else:
        restore.lost_work_s = max(float(staleness_steps), 0.0) * (
            step_seconds if step_seconds else PRIOR_STEP_S)
    return {"reroute": reroute, "reinstantiate": reinst, "restore": restore}
