"""Live per-mechanism signals the scorer consumes.

Each recovery mechanism becomes one ArmSignals record: its expected
recovery latency (measured history when the metrics plane has any,
documented priors otherwise — the source is carried so decisions are
honest about what they knew), its projected post-recovery throughput
retention, the work a checkpoint restore would replay, and feasibility
(a reroute around two correlated losses, or a restore with no durable
checkpoint, is not an option however cheap it looks).

Priors come in two flavors, and every arm records which one it used
(``prior_source``): the hardcoded PRIOR_LATENCY_S table below, or a
``learned_priors.json`` fitted from the incident corpus by
``oobleck_tpu.sim.priors`` and activated via ``$OOBLECK_POLICY_PRIORS``
(or an explicit ``priors_path``) — so a decision made from fitted priors
is distinguishable in forensics from one made from the shipped table.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field

from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.policy")

# Latency priors (seconds) used until a mechanism has measured history.
# reroute/reinstantiate-warm come from the degrade bench (~0.56 s / ~0.64 s
# on the reference shape, rounded up); reinstantiate-respawn and restore
# from the multiprocess recovery runs (~21 s respawn; restore adds durable
# read + re-instantiation on top).
PRIOR_LATENCY_S = {
    "reroute": 0.6,
    "reinstantiate": 0.7,          # warm in-place re-instantiation
    "reinstantiate_respawn": 21.0,  # multihost: respawn + re-init
    "restore": 25.0,
    # Grow-direction arms (JOIN incidents). absorb_spare only appends to
    # the spare pool (bookkeeping, no topology change); grow_dp is a warm
    # re-materialization at unchanged template size (one extra replica);
    # grow_reshape is a restore-across-reshape — durable read + larger-
    # template re-instantiation, priced like restore plus the re-match.
    "absorb_spare": 0.05,
    "grow_dp": 1.0,
    "grow_reshape": 26.0,
    # Slowdown-direction arms (SLOWDOWN incidents — a host alive but
    # persistently slow, PR 17). observe changes nothing (the cost is the
    # throughput the straggler keeps gating); drain/quarantine are a
    # proactive checkpoint-flush + reroute around a host that is still
    # able to flush cleanly — priced like a preemption drain, not like
    # recovering from a corpse.
    "observe": 0.0,
    "drain": 2.0,
    "quarantine": 2.0,
    # Pool-arbitration arms (cross-tenant borrow/reclaim incidents,
    # pool/arbiter.py). deny/hold change nothing (their cost is the SLO
    # debt the pressured tenant keeps paying); borrow_spare hands over
    # parked capacity (bookkeeping); borrow_drain preempts a training
    # host through the proactive drain + checkpoint flush (priced like
    # the slowdown drain plus serve-side attach); reclaim_grow returns
    # leased chips to training through the JOIN/grow path.
    "deny": 0.0,
    "borrow_spare": 0.1,
    "borrow_drain": 2.5,
    "hold": 0.0,
    "reclaim_grow": 1.2,
}
# Step-time prior when no measured step seconds are available yet (only
# used to price checkpoint staleness in lost-work seconds).
PRIOR_STEP_S = 1.0

# A drained straggler is readmitted once healthy; when its own MTBF is
# shorter than this horizon, the readmission is expected to cost another
# drain within it — the hazard that prices quarantine ahead of drain for
# a host that keeps failing (mirrors scorer.RISK_HORIZON_S, duplicated
# here because the scorer imports this module).
READMIT_HORIZON_S = 60.0

# Histogram families that hold measured recovery latencies by mechanism.
_LATENCY_HISTOGRAMS = (
    "oobleck_degrade_recovery_seconds",
    "oobleck_policy_measured_recovery_seconds",
)

# Path to a learned_priors.json fitted from the incident corpus (see
# oobleck_tpu/sim/priors.py); unset means the hardcoded table above.
ENV_PRIORS = "OOBLECK_POLICY_PRIORS"
# The priors-file format version this loader understands.
PRIORS_VERSION = 1

# (path, mtime) -> parsed latency table, so build_arms on the decision hot
# path never re-reads an unchanged file.
_priors_cache: dict = {"path": None, "mtime": None, "latency": None}


def learned_priors(path: str | None = None) -> tuple[dict, str] | None:
    """(latency_s table, "learned:<path>") from an explicit ``path`` or
    ``$OOBLECK_POLICY_PRIORS``; None when unset, unreadable, or of an
    unknown version (logged once per file change, never raised — a bad
    priors file must not take down the decision path)."""
    path = path or os.environ.get(ENV_PRIORS, "").strip() or None
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    if _priors_cache["path"] == path and _priors_cache["mtime"] == mtime:
        lat = _priors_cache["latency"]
        return (lat, f"learned:{path}") if lat else None
    _priors_cache.update(path=path, mtime=mtime, latency=None)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as e:
        logger.warning("policy: cannot read priors file %s: %s", path, e)
        return None
    if not isinstance(rec, dict) or rec.get("version") != PRIORS_VERSION:
        logger.warning("policy: skipping priors file %s: unknown version %r",
                       path, rec.get("version") if isinstance(rec, dict)
                       else type(rec).__name__)
        return None
    latency = {k: float(v) for k, v in (rec.get("latency_s") or {}).items()
               if isinstance(v, (int, float)) and v > 0}
    if not latency:
        logger.warning("policy: priors file %s has no usable latency_s", path)
        return None
    _priors_cache["latency"] = latency
    return latency, f"learned:{path}"


def priors_provenance(path: str | None = None) -> dict:
    """Which priors the next decision would fall back to — surfaced in the
    /status policy block so fitted-priors deployments are visible."""
    lp = learned_priors(path)
    if lp is not None:
        return {"source": lp[1], "mechanisms": sorted(lp[0])}
    return {"source": "hardcoded", "mechanisms": sorted(PRIOR_LATENCY_S)}


@dataclass
class ArmSignals:
    """Everything the scorer needs to know about one recovery mechanism
    for one incident."""

    mechanism: str
    latency_s: float
    latency_source: str            # "measured" | "prior"
    retention: float               # projected throughput after recovery
    lost_work_s: float = 0.0       # replayed work (checkpoint restore)
    in_memory: bool = True         # state survives in RAM -> churn risk
    feasible: bool = True
    reason: str = ""               # why infeasible ("" when feasible)
    prior_source: str = ""         # "hardcoded" | "learned:<path>" | ""
    # Cross-tenant terms (pool arbitration; zero on single-tenant arms).
    # slo_debt_s rides arms that leave a pressured tenant's SLO unrelieved
    # (deny a borrow, reclaim under live pressure); preempt_cost_s rides
    # arms that take running capacity away from a tenant (borrow_drain).
    slo_debt_s: float = 0.0
    preempt_cost_s: float = 0.0

    def as_record(self) -> dict:
        return {
            "latency_s": round(self.latency_s, 6),
            "latency_source": self.latency_source,
            "prior_source": self.prior_source,
            "retention": round(self.retention, 6),
            "lost_work_s": round(self.lost_work_s, 6),
            "slo_debt_s": round(self.slo_debt_s, 6),
            "preempt_cost_s": round(self.preempt_cost_s, 6),
            "feasible": self.feasible,
            "reason": self.reason,
        }


def measured_latency(mechanism: str, registry=None) -> float | None:
    """Mean measured recovery latency for a mechanism across the metric
    families that observe it, or None with no history."""
    reg = registry or metrics.registry()
    total = count = 0.0
    for name in _LATENCY_HISTOGRAMS:
        # Reads families registered (literally) elsewhere; the loop
        # variable is what makes the name dynamic here.
        # oobleck: allow[OBL005] -- iterates the registered name list
        for s in reg.histogram(name, "").series():
            if s["labels"].get("mechanism") == mechanism and s["count"]:
                total += s["sum"]
                count += s["count"]
    return total / count if count else None


def _latency(mechanism: str, prior_key: str, overrides, registry,
             priors_path=None):
    """(seconds, latency_source, prior_source). Measurement always wins
    (EWMA override, then histogram history); the prior fallback prefers a
    corpus-fitted table over the hardcoded one and names which it used."""
    if overrides and mechanism in overrides:
        return float(overrides[mechanism]), "measured", ""
    m = measured_latency(mechanism, registry)
    if m is not None:
        return m, "measured", ""
    lp = learned_priors(priors_path)
    if lp is not None and prior_key in lp[0]:
        return lp[0][prior_key], "prior", lp[1]
    return PRIOR_LATENCY_S[prior_key], "prior", "hardcoded"


def build_arms(*,
               multihost: bool = False,
               warm_reinstantiate: bool | None = None,
               degrade_enabled: bool = True,
               correlated: bool = False,
               reroute_retention: float | None = None,
               reroute_feasible: bool = True,
               reroute_reason: str = "",
               survivor_frac: float = 1.0,
               staleness_steps: float | None = None,
               step_seconds: float | None = None,
               latency_overrides: dict[str, float] | None = None,
               registry=None,
               priors_path: str | None = None) -> dict[str, ArmSignals]:
    """Assemble the three arms for one incident.

    staleness_steps is None when there is no durable checkpoint (restore
    infeasible), else current_step - last_durable_step. reroute_retention
    is the degrade planner's replay-projected survivor throughput when a
    projection exists; survivor_frac ((n-lost)/n) is the fallback for it
    and the default for the other in-memory arm — re-instantiated
    templates run on the same survivors, so absent measurements the arms
    are not fabricated apart on retention.
    """
    if warm_reinstantiate is None:
        warm_reinstantiate = not multihost

    reroute = ArmSignals(
        mechanism="reroute",
        latency_s=0.0, latency_source="",
        retention=(reroute_retention if reroute_retention is not None
                   else survivor_frac),
    )
    reroute.latency_s, reroute.latency_source, reroute.prior_source = \
        _latency("reroute", "reroute", latency_overrides, registry,
                 priors_path)
    if not degrade_enabled:
        reroute.feasible, reroute.reason = False, "degrade_disabled"
    elif correlated:
        reroute.feasible, reroute.reason = False, "correlated_failure"
    elif not reroute_feasible:
        reroute.feasible, reroute.reason = False, (reroute_reason
                                                   or "reroute_infeasible")
    reinst = ArmSignals(
        mechanism="reinstantiate",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
    )
    reinst.latency_s, reinst.latency_source, reinst.prior_source = _latency(
        "reinstantiate",
        "reinstantiate" if warm_reinstantiate else "reinstantiate_respawn",
        latency_overrides, registry, priors_path)

    restore = ArmSignals(
        mechanism="restore",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
        in_memory=False,
    )
    restore.latency_s, restore.latency_source, restore.prior_source = \
        _latency("restore", "restore", latency_overrides, registry,
                 priors_path)
    if staleness_steps is None:
        restore.feasible, restore.reason = False, "no_durable_checkpoint"
    else:
        restore.lost_work_s = max(float(staleness_steps), 0.0) * (
            step_seconds if step_seconds else PRIOR_STEP_S)
    return {"reroute": reroute, "reinstantiate": reinst, "restore": restore}


def build_grow_arms(*,
                    joined_count: int,
                    current_hosts: int,
                    dp_feasible: bool = True,
                    dp_reason: str = "",
                    staleness_steps: float | None = None,
                    step_seconds: float | None = None,
                    latency_overrides: dict[str, float] | None = None,
                    registry=None,
                    priors_path: str | None = None) -> dict[str, ArmSignals]:
    """Assemble the three GROW arms for one JOIN incident.

    Retention is measured against the POST-grow throughput ceiling: the
    scorer's degraded term then prices the gain an arm forgoes by not
    absorbing the arrivals, with the same amortization horizon a shrink
    decision uses — except here the horizon is the arriving host's
    expected LIFETIME (a spot host that will vanish in 30 s cannot
    amortize a 26 s reshape, so absorb_spare wins; a long-lived arrival
    flips it). The in_memory flag keeps the churn hedge: grow_dp and
    grow_reshape commit live state onto the newcomer, so its early death
    schedules the next recovery; parking a spare risks nothing.

    ``dp_feasible`` is the planner's verdict on whether the arrivals can
    form a whole extra replica of an already-instantiated template size;
    ``staleness_steps`` prices grow_reshape's restore-across-reshape
    rollback (None = no durable checkpoint: the reshape falls back to a
    live-state re-instantiation, which replays nothing).
    """
    n, k = max(int(current_hosts), 0), max(int(joined_count), 0)
    kept = (n / (n + k)) if (n + k) else 1.0

    absorb = ArmSignals(
        mechanism="absorb_spare",
        latency_s=0.0, latency_source="",
        retention=kept,
        in_memory=False,
    )
    absorb.latency_s, absorb.latency_source, absorb.prior_source = _latency(
        "absorb_spare", "absorb_spare", latency_overrides, registry,
        priors_path)

    grow_dp = ArmSignals(
        mechanism="grow_dp",
        latency_s=0.0, latency_source="",
        retention=1.0,
    )
    grow_dp.latency_s, grow_dp.latency_source, grow_dp.prior_source = \
        _latency("grow_dp", "grow_dp", latency_overrides, registry,
                 priors_path)
    if not dp_feasible:
        grow_dp.feasible, grow_dp.reason = False, (dp_reason
                                                   or "no_template_fit")

    reshape = ArmSignals(
        mechanism="grow_reshape",
        latency_s=0.0, latency_source="",
        retention=1.0,
    )
    reshape.latency_s, reshape.latency_source, reshape.prior_source = \
        _latency("grow_reshape", "grow_reshape", latency_overrides,
                 registry, priors_path)
    if staleness_steps is not None:
        reshape.lost_work_s = max(float(staleness_steps), 0.0) * (
            step_seconds if step_seconds else PRIOR_STEP_S)
    return {"absorb_spare": absorb, "grow_dp": grow_dp,
            "grow_reshape": reshape}


def build_slowdown_arms(*,
                        slowdown_ratio: float,
                        survivor_frac: float,
                        host_mtbf_s: float | None = None,
                        host_failures: int = 0,
                        latency_overrides: dict[str, float] | None = None,
                        registry=None,
                        priors_path: str | None = None
                        ) -> dict[str, ArmSignals]:
    """Assemble the three SLOWDOWN arms for one gray-failure incident.

    A straggler gates the whole synchronous fleet, so *observe* retains
    ``1/slowdown_ratio`` of throughput — and keeps live state on a host
    whose degradation usually precedes death (``in_memory=True``: the
    scorer's churn term prices exactly that hazard, rising with the sick
    host's worsening MTBF — the drain-before-it-dies signal). *drain*
    flushes a checkpoint on the way out (``in_memory=False``: nothing is
    left at risk) and runs the survivors at full speed, paying
    ``survivor_frac`` retention for the lost capacity; a drained host
    with a short MTBF is expected to be readmitted and drained again
    within READMIT_HORIZON_S, priced as ``lost_work_s``. *quarantine* is
    drain plus barring readmission — feasible only for a host with
    observed failure history (quarantining a first-time straggler on
    telemetry alone would be acting on one signal)."""
    ratio = max(float(slowdown_ratio), 1.0)

    observe = ArmSignals(
        mechanism="observe",
        latency_s=0.0, latency_source="",
        retention=1.0 / ratio,
    )
    observe.latency_s, observe.latency_source, observe.prior_source = \
        _latency("observe", "observe", latency_overrides, registry,
                 priors_path)

    drain = ArmSignals(
        mechanism="drain",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
        in_memory=False,
    )
    drain.latency_s, drain.latency_source, drain.prior_source = _latency(
        "drain", "drain", latency_overrides, registry, priors_path)
    if host_mtbf_s is not None and host_mtbf_s <= READMIT_HORIZON_S:
        drain.lost_work_s = drain.latency_s

    quarantine = ArmSignals(
        mechanism="quarantine",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
        in_memory=False,
    )
    quarantine.latency_s, quarantine.latency_source, \
        quarantine.prior_source = _latency(
            "quarantine", "quarantine", latency_overrides, registry,
            priors_path)
    if host_failures < 1:
        quarantine.feasible, quarantine.reason = False, "no_failure_history"
    return {"observe": observe, "drain": drain, "quarantine": quarantine}


def build_borrow_arms(*,
                      chips: int,
                      train_hosts: int,
                      spare_hosts: int = 0,
                      min_train_hosts: int = 1,
                      slo_debt_s: float = 0.0,
                      drain_cost_s: float | None = None,
                      latency_overrides: dict[str, float] | None = None,
                      registry=None,
                      priors_path: str | None = None
                      ) -> dict[str, ArmSignals]:
    """Assemble the three BORROW arms for one cross-tenant pressure incident
    (a serve replica group asking the pool arbiter for `chips` hosts).

    The cross-tenant asymmetry lives in two terms: *deny* leaves training
    whole (retention 1.0) but the pressured tenant keeps paying its SLO
    debt — ``slo_debt_s`` is the requester's projected seconds of
    deadline-missed work over the amortization window, charged to every
    arm that does NOT relieve the pressure. *borrow_spare* relieves it
    from parked capacity (nobody pays); *borrow_drain* relieves it by
    preempting training hosts through the proven proactive-drain path —
    the training tenant pays ``preempt_cost_s`` (the drain + checkpoint
    flush, measured when history exists) plus degraded retention for the
    lease's remaining lifetime (the caller passes that lifetime as the
    scorer's ``mtbf_s`` so the amortization window IS the lease). deny is
    always feasible: the arbiter can always say no, and the requester
    sheds load through its own admission queue."""
    n, k = max(int(train_hosts), 0), max(int(chips), 1)
    survivor_frac = ((n - k) / n) if n else 0.0

    deny = ArmSignals(
        mechanism="deny",
        latency_s=0.0, latency_source="",
        retention=1.0,
        in_memory=False,
        slo_debt_s=max(float(slo_debt_s), 0.0),
    )
    deny.latency_s, deny.latency_source, deny.prior_source = _latency(
        "deny", "deny", latency_overrides, registry, priors_path)

    spare = ArmSignals(
        mechanism="borrow_spare",
        latency_s=0.0, latency_source="",
        retention=1.0,
        in_memory=False,
    )
    spare.latency_s, spare.latency_source, spare.prior_source = _latency(
        "borrow_spare", "borrow_spare", latency_overrides, registry,
        priors_path)
    if int(spare_hosts) < k:
        spare.feasible, spare.reason = False, "no_spare_capacity"

    drain = ArmSignals(
        mechanism="borrow_drain",
        latency_s=0.0, latency_source="",
        retention=survivor_frac,
        in_memory=False,
    )
    drain.latency_s, drain.latency_source, drain.prior_source = _latency(
        "borrow_drain", "borrow_drain", latency_overrides, registry,
        priors_path)
    drain.preempt_cost_s = (float(drain_cost_s) if drain_cost_s is not None
                            else drain.latency_s)
    if n - k < max(int(min_train_hosts), 0):
        drain.feasible, drain.reason = False, "train_floor"
    return {"deny": deny, "borrow_spare": spare, "borrow_drain": drain}


def build_reclaim_arms(*,
                       leased_hosts: int,
                       train_hosts: int,
                       slo_debt_s: float = 0.0,
                       lease_expired: bool = False,
                       latency_overrides: dict[str, float] | None = None,
                       registry=None,
                       priors_path: str | None = None
                       ) -> dict[str, ArmSignals]:
    """Assemble the two RECLAIM arms for one lease-end decision (off-peak
    sweep, early release, or expiry).

    *hold* keeps the lease with the borrower: training stays degraded
    (retention = its shrunken fraction, amortized over the remaining
    lease passed as ``mtbf_s``) but a borrower still under pressure pays
    nothing — infeasible once the lease has expired, since a lease that
    never ends is an allocation. *reclaim_grow* returns the chips to
    training through the JOIN/grow path; if the borrower's pressure has
    NOT passed, its ``slo_debt_s`` rides this arm (reclaiming re-exposes
    the borrower to the peak), which is what makes the arbiter hold
    through the peak and reclaim off-peak."""
    n, k = max(int(train_hosts), 0), max(int(leased_hosts), 1)
    degraded_frac = (n / (n + k)) if (n + k) else 1.0

    hold = ArmSignals(
        mechanism="hold",
        latency_s=0.0, latency_source="",
        retention=degraded_frac,
        in_memory=False,
    )
    hold.latency_s, hold.latency_source, hold.prior_source = _latency(
        "hold", "hold", latency_overrides, registry, priors_path)
    if lease_expired:
        hold.feasible, hold.reason = False, "lease_expired"

    reclaim = ArmSignals(
        mechanism="reclaim_grow",
        latency_s=0.0, latency_source="",
        retention=1.0,
        in_memory=False,
        slo_debt_s=max(float(slo_debt_s), 0.0),
    )
    reclaim.latency_s, reclaim.latency_source, reclaim.prior_source = \
        _latency("reclaim_grow", "reclaim_grow", latency_overrides,
                 registry, priors_path)
    return {"hold": hold, "reclaim_grow": reclaim}
