"""Shared policy for JAX's persistent compilation cache.

The CPU test/gate environments are compile-bound, so the cache is ON by
default; every consumer (tests/conftest.py, the multi-process test worlds,
the __graft_entry__ driver gate, the recovery precompiler) resolves the
SAME directory through this helper so subprocess worlds share entries with
the in-process suite.

Knobs:
  * OOBLECK_JAX_CC=0 disables the cache everywhere;
  * JAX_COMPILATION_CACHE_DIR overrides the location (taken verbatim —
    permissions and sharing are then the operator's call).

The default dir is per-user (created 0700: cached executables are code,
and a world-writable shared dir would let any local user plant entries
another user's training job deserializes and runs), and keyed by jaxlib
version PLUS a digest of the host CPU's feature flags: XLA:CPU specializes
codegen to the detected ISA (AVX-512 vs AVX2 ...), so entries written on
one machine can be subtly wrong on another when /tmp is shared or images
are snapshotted across heterogeneous fleets. A poisoned entry CAN wedge
execution (observed once: a hang inside a float(loss) readback on a cached
fused program) — the remedy is removing the cache dir.
"""

from __future__ import annotations

import getpass
import hashlib
import logging
import os
import platform
import tempfile
import zlib

logger = logging.getLogger("oobleck.compile_cache")

_cpu_sig_cache: str | None = None

# Compressed-entry magics: jax's compilation cache compresses serialized
# executables with zstandard when importable, zlib otherwise.
_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"
_SCRUB_STAMP = ".oobleck_scrub_stamp"


def cache_event(event: str, n: int = 1) -> None:
    """Count one persistent-cache event (enabled/disabled/hit/miss) in the
    metrics registry. Hits/misses come from the recovery precompiler (the
    one consumer that can tell a deserialization from a cold compile);
    enable/disable comes from ensure_persistent_cache."""
    if n <= 0:
        return
    from oobleck_tpu.utils import metrics

    metrics.registry().counter(
        "oobleck_compile_cache_events_total",
        "Persistent compile-cache events by kind").inc(n, event=event)


def host_cpu_signature() -> str:
    """Short stable digest of the CPU features XLA:CPU specializes against.

    Linux: the `flags`/`Features` lines of /proc/cpuinfo (one physical CPU's
    worth — cores are homogeneous for codegen purposes). Elsewhere: the
    machine/processor identifiers. Cached per process."""
    global _cpu_sig_cache
    if _cpu_sig_cache is not None:
        return _cpu_sig_cache
    feature_text = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                key = line.split(":", 1)[0].strip().lower()
                if key in ("flags", "features"):
                    feature_text = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    if not feature_text:
        feature_text = f"{platform.machine()}/{platform.processor()}"
    raw = f"{platform.machine()}|{feature_text}"
    _cpu_sig_cache = hashlib.sha256(raw.encode()).hexdigest()[:12]
    return _cpu_sig_cache


def persistent_cache_dir() -> str | None:
    """Resolved cache dir, or None when disabled (OOBLECK_JAX_CC=0).

    The default location is created here with mode 0700 so every consumer
    (including `_base_env` in the multi-process tests, which exports it to
    subprocess worlds) gets a directory that already exists with the right
    permissions."""
    if os.environ.get("OOBLECK_JAX_CC", "1") == "0":
        return None
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return os.environ["JAX_COMPILATION_CACHE_DIR"]
    import jaxlib

    try:
        user = getpass.getuser()
    except (KeyError, OSError):
        user = f"uid{os.getuid()}"
    d = os.path.join(
        tempfile.gettempdir(),
        f"oobleck_jax_cc_{user}",
        f"{jaxlib.__version__}_{host_cpu_signature()}",
    )
    os.makedirs(d, mode=0o700, exist_ok=True)
    # makedirs mode is masked by umask and ignored for pre-existing dirs;
    # chmod makes 0700 unconditional on the user-level parent.
    os.chmod(os.path.dirname(d), 0o700)
    os.chmod(d, 0o700)
    return d


def _entry_corrupt(path: str) -> bool:
    """True when a cache entry is PROVABLY corrupt: empty, or a truncated/
    damaged compressed stream. Entries in a format we cannot validate
    (zstd without the zstandard module, or an unrecognized header) are
    left alone — eviction must never eat a valid entry."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return False
    if not blob:
        return True  # a crash mid-write left an empty entry
    if blob[:4] == _ZSTD_MAGIC:
        try:
            import zstandard
        except ImportError:
            return False
        try:
            dec = zstandard.ZstdDecompressor().decompressobj()
            for i in range(0, len(blob), 1 << 20):
                dec.decompress(blob[i:i + (1 << 20)])
            return False
        except zstandard.ZstdError:
            return True
    if blob[0] != 0x78:  # zlib header byte
        return False
    try:
        dec = zlib.decompressobj()
        for i in range(0, len(blob), 1 << 20):
            dec.decompress(blob[i:i + (1 << 20)])
        # A truncated stream decompresses without error but never reaches
        # EOF — the exact state a killed writer leaves behind, and the one
        # that wedges deserialization at use time.
        return not dec.eof
    except zlib.error:
        return True


def scrub_persistent_cache(d: str | None = None, *, force: bool = False) -> int:
    """Detect and evict poisoned/corrupt persistent-cache entries.

    A cache entry that fails to decompress can wedge execution at USE time
    (observed: a hang inside a float(loss) readback on a cached fused
    program — the failure mode that broke the fused multiprocess recovery
    test), so corruption is caught at startup instead: every entry newer
    than the last scrub is validated and deleted on failure (JAX then
    recompiles and rewrites it). Returns the number evicted.

    Incremental via a stamp file so repeated startups only pay for new
    entries; `force=True` rescans everything."""
    d = d if d is not None else persistent_cache_dir()
    if d is None or not os.path.isdir(d):
        return 0
    stamp = os.path.join(d, _SCRUB_STAMP)
    last = 0.0
    if not force:
        try:
            last = os.stat(stamp).st_mtime
        except OSError:
            pass
    evicted = 0
    for name in os.listdir(d):
        if name.startswith("."):
            continue
        path = os.path.join(d, name)
        try:
            st = os.stat(path)
        except OSError:
            continue
        if not os.path.isfile(path) or (not force and st.st_mtime < last):
            continue
        if _entry_corrupt(path):
            try:
                os.unlink(path)
            except OSError:
                continue
            evicted += 1
            logger.warning(
                "evicted corrupt persistent-cache entry %s (%d B): "
                "deserialization would fail or hang; it will recompile",
                name, st.st_size)
    try:
        with open(stamp, "w") as f:
            f.write("scrub marker; entries older than this mtime are validated\n")
    except OSError:
        pass
    cache_event("evicted_corrupt", evicted)
    return evicted


def ensure_persistent_cache() -> str | None:
    """Point JAX's persistent compilation cache at `persistent_cache_dir()`.

    Idempotent; returns the effective dir (None when disabled). The warm
    recovery path depends on this: AOT-compiling a predicted plan only
    helps a later (re)compile if the serialized executable lands in a
    persistent cache both sides share (execution/precompile.py)."""
    d = persistent_cache_dir()
    if d is None:
        cache_event("disabled")
        return None
    import jax

    if jax.config.jax_compilation_cache_dir != d:
        # First enable in this process: validate entries written since the
        # last scrub before anything deserializes them.
        scrub_persistent_cache(d)
        jax.config.update("jax_compilation_cache_dir", d)
        cache_event("enabled")
    return d
