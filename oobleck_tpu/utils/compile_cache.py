"""Shared policy for JAX's persistent compilation cache.

The CPU test/gate environments are compile-bound, so the cache is ON by
default; every consumer (tests/conftest.py, the multi-process test worlds,
the __graft_entry__ driver gate) resolves the SAME directory through this
helper so subprocess worlds share entries with the in-process suite.

Knobs:
  * OOBLECK_JAX_CC=0 disables the cache everywhere;
  * JAX_COMPILATION_CACHE_DIR overrides the location.

The default dir is jaxlib-versioned to bound cross-version aliasing. A
poisoned entry CAN wedge execution (observed once: a hang inside a
float(loss) readback on a cached fused program) — the remedy is
`rm -rf /tmp/oobleck_jax_cc*`.
"""

from __future__ import annotations

import os


def persistent_cache_dir() -> str | None:
    """Resolved cache dir, or None when disabled (OOBLECK_JAX_CC=0)."""
    if os.environ.get("OOBLECK_JAX_CC", "1") == "0":
        return None
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return os.environ["JAX_COMPILATION_CACHE_DIR"]
    import jaxlib

    return f"/tmp/oobleck_jax_cc_{jaxlib.__version__}"
