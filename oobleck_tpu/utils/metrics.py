"""Dependency-free cluster metrics plane.

One registry per process holds counters, gauges, and fixed-bucket
histograms (all label-aware, all thread-safe). Two sinks:

- Prometheus text exposition (``render_prometheus``), served cluster-wide
  by the master's ``MetricsHTTPServer`` (/metrics and /status);
- an append-only JSONL file under ``OOBLECK_METRICS_DIR``
  (``dump_jsonl``), consumed by bench.py for tokens/sec, MFU, and
  recovery-latency percentiles.

Snapshots are plain JSON dicts so they travel over the elastic protocol
(worker -> agent mp pipe -> master TCP METRICS push) and merge on the
master with ``host``/``role`` labels attached.

The module also hosts the control-plane flight recorder: a bounded ring
of recent events (registrations, heartbeats, reconfigurations, chaos
injections) that is dumped to ``OOBLECK_METRICS_DIR/flight-*.jsonl``
when a failure is detected or a recovery deadline is breached, turning
every chaos-test failure into a self-contained postmortem artifact.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)

ENV_METRICS_DIR = "OOBLECK_METRICS_DIR"
ENV_METRICS_PORT = "OOBLECK_METRICS_PORT"
ENV_FLIGHT_CAPACITY = "OOBLECK_FLIGHT_CAPACITY"
ENV_STRICT_REGISTRY = "OOBLECK_STRICT_REGISTRY"


def _strict_registry_check(kind: str, name: str) -> None:
    """Debug/test-run schema enforcement: with OOBLECK_STRICT_REGISTRY=1,
    a metric family or flight-event kind missing from the generated
    ``obs/registry.py`` raises instead of minting a silent, never-read
    parallel series (the OBL005 invariant, enforced at runtime for names
    lint cannot see). Off by default: tests record ad-hoc event kinds.
    Fail-open on import problems — the registry module is generated, and
    a half-built checkout must not take the metrics plane down."""
    if os.environ.get(ENV_STRICT_REGISTRY, "") not in ("1", "true", "yes"):
        return
    try:
        # Deferred import: obs -> metrics at module load, never the
        # reverse (registry is leaf, but the package __init__ is not).
        from oobleck_tpu.obs import registry
        allowed = (registry.METRIC_FAMILIES if kind == "metric"
                   else registry.FLIGHT_EVENT_KINDS)
    except (ImportError, AttributeError):
        return
    if name not in allowed:
        raise ValueError(
            f"{kind} name {name!r} is not in obs/registry.py — a typo "
            f"would emit a series nothing reads; regenerate with "
            f"`make gen-registry` if the name is intentional")

# Step/region wall times: sub-millisecond CPU smoke runs up to multi-second
# real steps.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# Recovery latencies: the interesting range is seconds to minutes (the
# RECOVERY_DEADLINE budget in chaos tests is tens of seconds).
RECOVERY_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 15.0, 30.0,
                    60.0, 120.0, 300.0, 600.0)
# Checkpoint train-loop stalls: the async writer's enqueue is tens of
# microseconds (reference capture, no device_get), the sync baseline is
# the full write — the histogram must resolve both ends to evidence the
# "<25% of synchronous stall" acceptance bar (oobleck_tpu/ckpt).
CKPT_STALL_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                      0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                      10.0, 30.0, 60.0)
# Serving latencies (TTFT, per-token decode, hot-reload pause): per-token
# times are sub-millisecond-to-tens-of-ms on warm caches, TTFT includes a
# prefill (up to seconds when it triggers a compile), and the reload-pause
# claim ("well below one checkpoint restore") needs sub-millisecond
# resolution at the bottom end.
SERVE_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                         0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                         5.0, 10.0, 30.0)


def _label_key(labels: dict[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _format_labels(pairs) -> str:
    if not pairs:
        return ""
    body = ",".join('%s="%s"' % (k, str(v).replace("\\", "\\\\")
                                 .replace('"', '\\"').replace("\n", "\\n"))
                    for k, v in pairs)
    return "{" + body + "}"


class _Metric:
    """Base for one named metric family; children keyed by label values."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def _child(self, labels: dict[str, str], factory):
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = factory()
                self._children[key] = child
            return child

    def series(self) -> list[dict]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        cell = self._child(labels, lambda: [0.0])
        with self._lock:
            cell[0] += amount

    def value(self, **labels) -> float:
        cell = self._child(labels, lambda: [0.0])
        with self._lock:
            return cell[0]

    def series(self) -> list[dict]:
        with self._lock:
            return [{"labels": dict(key), "value": cell[0]}
                    for key, cell in self._children.items()]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        cell = self._child(labels, lambda: [0.0])
        with self._lock:
            cell[0] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        cell = self._child(labels, lambda: [0.0])
        with self._lock:
            cell[0] += amount

    def value(self, **labels) -> float:
        cell = self._child(labels, lambda: [0.0])
        with self._lock:
            return cell[0]

    def series(self) -> list[dict]:
        with self._lock:
            return [{"labels": dict(key), "value": cell[0]}
                    for key, cell in self._children.items()]


class _HistCell:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        cell = self._child(labels, lambda: _HistCell(len(self.buckets)))
        with self._lock:
            for i, upper in enumerate(self.buckets):
                if value <= upper:
                    cell.counts[i] += 1
                    break
            cell.sum += value
            cell.count += 1

    def series(self) -> list[dict]:
        with self._lock:
            return [{"labels": dict(key), "buckets": list(self.buckets),
                     "counts": list(cell.counts), "sum": cell.sum,
                     "count": cell.count}
                    for key, cell in self._children.items()]


class Registry:
    """Thread-safe collection of metric families, keyed by name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, name: str, cls, help_text: str, **kwargs) -> _Metric:
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                _strict_registry_check("metric", name)
                metric = cls(name, help_text, **kwargs)
                self._metrics[name] = metric
            elif not isinstance(metric, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {metric.kind}")
            return metric

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(name, Counter, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(name, Gauge, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, help_text, buckets=buckets)

    def snapshot(self) -> dict:
        """JSON-serializable view: ships over the wire and into JSONL."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {
            "t": time.time(),
            "metrics": [{"name": m.name, "type": m.kind, "help": m.help,
                         "series": m.series()} for m in metrics],
        }

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


def render_prometheus(snapshots: list[dict],
                      extra_labels: list[dict[str, str]] | None = None,
                      ) -> str:
    """Render one or more registry snapshots as Prometheus text.

    ``extra_labels[i]`` (e.g. {"host": ..., "role": ...}) is attached to
    every series of ``snapshots[i]`` so the master can expose a merged
    cluster-wide view without name collisions.
    """
    families: dict[str, dict] = {}
    for i, snap in enumerate(snapshots):
        extra = (extra_labels or [{}] * len(snapshots))[i] or {}
        for metric in snap.get("metrics", []):
            fam = families.setdefault(
                metric["name"],
                {"type": metric["type"], "help": metric.get("help", ""),
                 "series": []})
            for s in metric.get("series", []):
                merged = dict(extra)
                merged.update(s.get("labels", {}))
                families[metric["name"]]["series"].append(
                    {**s, "labels": merged})
            fam["type"] = metric["type"]

    lines: list[str] = []
    for name in sorted(families):
        fam = families[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam["series"]:
            pairs = _label_key(s.get("labels", {}))
            if fam["type"] == "histogram":
                cumulative = 0
                for upper, cnt in zip(s["buckets"], s["counts"]):
                    cumulative += cnt
                    bucket_pairs = pairs + (("le", repr(float(upper))),)
                    lines.append("%s_bucket%s %d" % (
                        name, _format_labels(bucket_pairs), cumulative))
                inf_pairs = pairs + (("le", "+Inf"),)
                lines.append("%s_bucket%s %d" % (
                    name, _format_labels(inf_pairs), s["count"]))
                lines.append("%s_sum%s %g" % (
                    name, _format_labels(pairs), s["sum"]))
                lines.append("%s_count%s %d" % (
                    name, _format_labels(pairs), s["count"]))
            else:
                lines.append("%s%s %g" % (
                    name, _format_labels(pairs), s["value"]))
    return "\n".join(lines) + "\n"


def histogram_percentile(series: dict, q: float) -> float | None:
    """Estimate the q-th percentile (0..1) from one histogram series dict
    (as found in a snapshot) by linear interpolation within the bucket."""
    count = series.get("count", 0)
    if not count:
        return None
    target = q * count
    cumulative = 0
    lower = 0.0
    for upper, cnt in zip(series["buckets"], series["counts"]):
        if cumulative + cnt >= target:
            if cnt == 0:
                return float(upper)
            frac = (target - cumulative) / cnt
            return lower + (float(upper) - lower) * frac
        cumulative += cnt
        lower = float(upper)
    # Beyond the last finite bucket: best effort from the running mean.
    return max(lower, series["sum"] / count)


# ---------------------------------------------------------------------------
# process-global registry / role / sinks


_registry = Registry()
_role = "proc"
_role_lock = threading.Lock()


def registry() -> Registry:
    return _registry


def set_role(role: str) -> None:
    """Tag this process (master/agent/worker) for sink file names."""
    global _role
    with _role_lock:
        _role = role


def get_role() -> str:
    with _role_lock:
        return _role


def metrics_dir() -> str | None:
    d = os.environ.get(ENV_METRICS_DIR)
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
    except OSError as e:
        logger.warning("metrics: cannot create %s: %s", d, e)
        return None
    return d


def dump_jsonl(snapshot: dict | None = None) -> str | None:
    """Append one snapshot line to OOBLECK_METRICS_DIR/metrics-{role}-{pid}
    .jsonl. Returns the path, or None when the sink is disabled."""
    d = metrics_dir()
    if d is None:
        return None
    if snapshot is None:
        snapshot = _registry.snapshot()
    snapshot = dict(snapshot)
    snapshot.setdefault("role", get_role())
    path = os.path.join(d, f"metrics-{get_role()}-{os.getpid()}.jsonl")
    try:
        with open(path, "a") as f:
            f.write(json.dumps(snapshot) + "\n")
    except OSError as e:
        logger.warning("metrics: cannot append to %s: %s", path, e)
        return None
    return path


def read_jsonl_dir(d: str) -> list[dict]:
    """Load every snapshot line from metrics-*.jsonl under ``d``, tagging
    each with its source file (``_file``) — counters/histograms are
    per-process cumulative, so consumers aggregate the LAST snapshot per
    file. Malformed lines are skipped (a SIGKILLed writer can leave a torn
    tail)."""
    snapshots: list[dict] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return snapshots
    for name in names:
        if not (name.startswith("metrics-") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        snap = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(snap, dict):
                        snap["_file"] = name
                        snapshots.append(snap)
        except OSError:
            continue
    return snapshots


def latest_per_file(snapshots: list[dict]) -> list[dict]:
    """The last snapshot of each source file (see read_jsonl_dir)."""
    by_file: dict[str, dict] = {}
    for snap in snapshots:
        by_file[snap.get("_file", "")] = snap
    return list(by_file.values())


def find_series(snapshots: list[dict], name: str) -> list[dict]:
    """All series dicts of metric `name` across snapshots."""
    out = []
    for snap in snapshots:
        for m in snap.get("metrics", []):
            if m.get("name") == name:
                out.extend(m.get("series", []))
    return out


def merge_histogram_series(series: list[dict]) -> dict | None:
    """Sum histogram series (same bucket layout) into one, for cluster-wide
    percentiles; None when empty or bucket layouts disagree."""
    merged: dict | None = None
    for s in series:
        if "buckets" not in s:
            continue
        if merged is None:
            merged = {"buckets": list(s["buckets"]),
                      "counts": list(s["counts"]),
                      "sum": s["sum"], "count": s["count"]}
        elif merged["buckets"] == list(s["buckets"]):
            merged["counts"] = [a + b for a, b
                                in zip(merged["counts"], s["counts"])]
            merged["sum"] += s["sum"]
            merged["count"] += s["count"]
    return merged


# ---------------------------------------------------------------------------
# flight recorder


class FlightRecorder:
    """Bounded ring of recent control-plane events. ``dump()`` writes the
    whole ring to OOBLECK_METRICS_DIR/flight-{role}-{pid}-{seq}.jsonl."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            raw = os.environ.get(ENV_FLIGHT_CAPACITY, "")
            try:
                capacity = int(raw) if raw else 256
            except ValueError:
                logger.warning("metrics: malformed %s=%r ignored",
                               ENV_FLIGHT_CAPACITY, raw)
                capacity = 256
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(capacity, 1))
        self._seq = 0

    def record(self, event: str, **fields) -> None:
        _strict_registry_check("flight event", event)
        entry = {"t": time.time(), "event": event}
        entry.update(fields)
        with self._lock:
            self._ring.append(entry)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str) -> str | None:
        d = metrics_dir()
        if d is None:
            return None
        with self._lock:
            events = list(self._ring)
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            d, f"flight-{get_role()}-{os.getpid()}-{seq}.jsonl")
        try:
            with open(path, "w") as f:
                f.write(json.dumps({"t": time.time(), "event": "dump",
                                    "reason": reason,
                                    "role": get_role()}) + "\n")
                for entry in events:
                    f.write(json.dumps(entry) + "\n")
        except OSError as e:
            logger.warning("metrics: cannot write flight dump %s: %s",
                           path, e)
            return None
        logger.info("flight recorder dumped %d events to %s (%s)",
                    len(events), path, reason)
        return path


_flight = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _flight


# ---------------------------------------------------------------------------
# HTTP endpoint (master)


class MetricsHTTPServer:
    """Stdlib ThreadingHTTPServer serving /metrics (Prometheus text from
    ``metrics_fn``) and /status (JSON from ``status_fn``) on a daemon
    thread. Port 0 binds an ephemeral port; read ``.port`` after start."""

    def __init__(self, metrics_fn, status_fn, port: int = 0,
                 host: str = "0.0.0.0"):
        self._metrics_fn = metrics_fn
        self._status_fn = status_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # keep test logs quiet
                logger.debug("metrics http: " + fmt, *args)

            def do_GET(self):
                try:
                    if self.path.split("?")[0] == "/metrics":
                        body = outer._metrics_fn().encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif self.path.split("?")[0] == "/status":
                        body = json.dumps(outer._status_fn()).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:  # noqa: BLE001 — endpoint must never take the master down
                    logger.exception("metrics http handler failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="oobleck-metrics-http",
            daemon=True)

    def start(self) -> "MetricsHTTPServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
