"""Runtime tracing.

Capability match for the reference's torch-profiler annotations
(record_function regions around FSDP hooks, /root/reference/oobleck/
execution/layer.py:148-190) plus the TensorBoard wiring it lists as a dep
but never uses (SURVEY §5): jax.profiler spans around engine regions, and an
on-demand trace dump for a window of steps.

Enable with OOBLECK_TRACE_DIR=/path — the engine wraps steps in named
annotations and writes a perfetto-compatible trace for steps
[OOBLECK_TRACE_START, OOBLECK_TRACE_START + OOBLECK_TRACE_STEPS).
"""

from __future__ import annotations

import contextlib
import os

import jax


def annotate(name: str):
    """Named span visible in TPU profiler traces (and a no-op otherwise)."""
    return jax.profiler.TraceAnnotation(name)


class StepTracer:
    """Traces a configured window of training steps to OOBLECK_TRACE_DIR."""

    def __init__(self):
        self.trace_dir = os.environ.get("OOBLECK_TRACE_DIR")
        self.start = int(os.environ.get("OOBLECK_TRACE_START", "3"))
        self.steps = int(os.environ.get("OOBLECK_TRACE_STEPS", "3"))
        self._active = False

    def on_step(self, step: int) -> None:
        if not self.trace_dir:
            return
        if (not self._active and step >= self.start
                and step < self.start + self.steps):
            # >= so a checkpoint-resumed run past `start` still traces its
            # first post-resume window.
            jax.profiler.start_trace(self.trace_dir)
            self._active = True
        elif self._active and step >= self.start + self.steps:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
