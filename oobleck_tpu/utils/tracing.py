"""Runtime tracing.

Capability match for the reference's torch-profiler annotations
(record_function regions around FSDP hooks, /root/reference/oobleck/
execution/layer.py:148-190) plus the TensorBoard wiring it lists as a dep
but never uses (SURVEY §5): jax.profiler spans around engine regions, and an
on-demand trace dump for a window of steps.

Enable with OOBLECK_TRACE_DIR=/path — the engine wraps steps in named
annotations and writes a perfetto-compatible trace for steps
[OOBLECK_TRACE_START, OOBLECK_TRACE_START + OOBLECK_TRACE_STEPS). Set
OOBLECK_TRACE_EVERY=<n> to re-arm the window every n steps for long runs
(window k covers [START + k*EVERY, START + k*EVERY + STEPS)).

Lifecycle: the engine owns one StepTracer per train() and calls close()
from its finally AND from reconfigure() — a mid-window failure or topology
change must not leave a jax.profiler trace open (start_trace raises on
double-start, and an unclosed trace loses its buffered data).
"""

from __future__ import annotations

import contextlib
import logging
import os

import jax

logger = logging.getLogger("oobleck.tracing")


def annotate(name: str):
    """Named span visible in TPU profiler traces (and a no-op otherwise)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def traced(name: str, **attrs):
    """One region, both tracing planes: a jax.profiler annotation (shows in
    device traces captured by StepTracer) AND an obs span (shows in the
    distributed timeline, stitched to whatever trace is current/ambient)."""
    from oobleck_tpu.obs import spans

    # oobleck: allow[OBL005] -- generic helper, the caller owns the name
    with jax.profiler.TraceAnnotation(name), spans.span(name, **attrs):
        yield


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        logger.warning("ignoring malformed %s=%r", name, raw)
        return default


class StepTracer:
    """Traces windows of training steps to OOBLECK_TRACE_DIR."""

    def __init__(self):
        self.trace_dir = os.environ.get("OOBLECK_TRACE_DIR")
        self.start = _env_int("OOBLECK_TRACE_START", 3)
        self.steps = _env_int("OOBLECK_TRACE_STEPS", 3)
        # 0 = one window (legacy behavior); n > 0 re-arms every n steps.
        self.every = _env_int("OOBLECK_TRACE_EVERY", 0)
        self._active = False
        self._done = False  # one-shot mode: window consumed (or closed)

    def _window_start(self, step: int) -> int:
        if self.every > 0 and step >= self.start:
            k = (step - self.start) // self.every
            return self.start + k * self.every
        return self.start

    def on_step(self, step: int) -> None:
        if not self.trace_dir or self.steps <= 0:
            return
        ws = self._window_start(step)
        in_window = ws <= step < ws + self.steps
        if self._active:
            if not in_window:
                self._stop()
            else:
                return
        if self._done and self.every <= 0:
            return
        if in_window:
            try:
                jax.profiler.start_trace(self.trace_dir)
            except RuntimeError as e:
                # Another component holds a trace open; skip this window
                # rather than kill training.
                logger.warning("trace window skipped: %s", e)
                self._done = True
                return
            self._active = True

    def _stop(self) -> None:
        try:
            jax.profiler.stop_trace()
        except RuntimeError as e:
            logger.warning("stop_trace failed: %s", e)
        self._active = False
        if self.every <= 0:
            self._done = True

    def close(self) -> None:
        """Idempotent: stop an open window (engine shutdown/reconfigure).
        One-shot mode stays closed; periodic mode re-arms at the next
        window boundary."""
        if self._active:
            self._stop()
        if self.every <= 0:
            self._done = True
