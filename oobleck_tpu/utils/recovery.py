"""RECOVERY_DEADLINE accounting: structured wall-time marks for the
failure-detect -> broadcast -> respawn -> first-post-recovery-step chain.

Each stage of a recovery emits one machine-parseable log line:

    RECOVERY_DEADLINE {"event": "detect", "lost_ip": "...", "t": ...}

The chain crosses three processes (master detects, agent respawns, worker
steps), so the marks carry wall-clock epoch seconds and the lost host's ip
as the correlation key — a log scrape joins them into the end-to-end
recovery latency (processes on one machine share a clock; multi-machine
deployments need NTP-class sync, which TPU pods have).

``OOBLECK_RECOVERY_DEADLINE`` (seconds) arms an explicit budget: any mark
carrying an ``elapsed`` beyond it logs a LOUD deadline-exceeded line. The
deadline is accounting, not enforcement — recovery keeps going; the
operator (and the chaos tests) get a greppable breach signal.
"""

from __future__ import annotations

import json
import logging
import os
import time

logger = logging.getLogger("oobleck.recovery")

MARK = "RECOVERY_DEADLINE"
ENV_DEADLINE = "OOBLECK_RECOVERY_DEADLINE"

# Canonical event names, in chain order.
DETECT = "detect"          # master: failure observed (disconnect / deadline)
BROADCAST = "broadcast"    # master: RECONFIGURATION sent to survivors
NOTIFIED = "notified"      # agent: RECONFIGURATION received
RESPAWN = "respawn"        # agent: replacement worker launched
FIRST_STEP = "first_step"  # engine: first training step after recovery


def deadline_s() -> float | None:
    raw = os.environ.get(ENV_DEADLINE, "")
    try:
        return float(raw) if raw else None
    except ValueError:
        logger.warning("ignoring malformed %s=%r", ENV_DEADLINE, raw)
        return None


def mark(event: str, **fields) -> float:
    """Emit one structured recovery mark; returns the wall-clock stamp."""
    t = time.time()
    rec = {"event": event, "t": round(t, 3)}
    rec.update({k: v for k, v in fields.items() if v is not None})
    logger.warning("%s %s", MARK, json.dumps(rec, sort_keys=True))
    budget = deadline_s()
    elapsed = fields.get("elapsed")
    if budget is not None and elapsed is not None and elapsed > budget:
        logger.error(
            "%s EXCEEDED: %s took %.1fs against a %.1fs budget (%s)",
            MARK, event, elapsed, budget,
            json.dumps({k: v for k, v in fields.items() if k != "elapsed"},
                       sort_keys=True),
        )
    return t
