"""RECOVERY_DEADLINE accounting: structured wall-time marks for the
failure-detect -> broadcast -> respawn -> first-post-recovery-step chain.

Each stage of a recovery emits one machine-parseable log line:

    RECOVERY_DEADLINE {"event": "detect", "lost_ip": "...", "t": ...}

The chain crosses three processes (master detects, agent respawns, worker
steps), so the marks carry wall-clock epoch seconds and the lost host's ip
as the correlation key — a log scrape joins them into the end-to-end
recovery latency (processes on one machine share a clock; multi-machine
deployments need NTP-class sync, which TPU pods have).

``OOBLECK_RECOVERY_DEADLINE`` (seconds) arms an explicit budget: any mark
carrying an ``elapsed`` beyond it logs a LOUD deadline-exceeded line. The
deadline is accounting, not enforcement — recovery keeps going; the
operator (and the chaos tests) get a greppable breach signal.
"""

from __future__ import annotations

import json
import logging
import os
import time

from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.recovery")

MARK = "RECOVERY_DEADLINE"
ENV_DEADLINE = "OOBLECK_RECOVERY_DEADLINE"

# Canonical event names, in chain order.
DETECT = "detect"          # master: failure observed (disconnect / deadline)
BROADCAST = "broadcast"    # master: RECONFIGURATION sent to survivors
NOTIFIED = "notified"      # agent: RECONFIGURATION received
RESPAWN = "respawn"        # agent: replacement worker launched
FIRST_STEP = "first_step"  # engine: first training step after recovery


def deadline_s() -> float | None:
    raw = os.environ.get(ENV_DEADLINE, "")
    try:
        return float(raw) if raw else None
    except ValueError:
        logger.warning("ignoring malformed %s=%r", ENV_DEADLINE, raw)
        return None


def _latency_histogram() -> metrics.Histogram:
    return metrics.registry().histogram(
        "oobleck_recovery_latency_seconds",
        "Per-chain-stage recovery latency (labeled by stage)",
        buckets=metrics.RECOVERY_BUCKETS,
    )


def observe_latency(seconds: float, stage: str) -> None:
    """Feed the recovery-latency histogram outside the mark chain (e.g. the
    engine's in-place reconfigure wall time)."""
    _latency_histogram().observe(float(seconds), stage=stage)


def mark(event: str, **fields) -> float:
    """Emit one structured recovery mark; returns the wall-clock stamp.

    Besides the greppable log line, every mark increments the
    ``oobleck_recovery_marks_total`` counter, and marks that carry an
    ``elapsed`` observe it into the per-stage recovery-latency histogram —
    the /metrics view of the same chain the log scrape reconstructs."""
    t = time.time()
    rec = {"event": event, "t": round(t, 3)}
    rec.update({k: v for k, v in fields.items() if v is not None})
    logger.warning("%s %s", MARK, json.dumps(rec, sort_keys=True))
    # Mirror the mark as a point span: when an incident trace is ambient
    # (the engine pins it around reconfigure), the mark stitches into the
    # same Perfetto timeline the postmortem report renders. Imported here,
    # not at module top, purely to keep this leaf module import-light.
    from oobleck_tpu.obs import spans as _spans

    # oobleck: allow[OBL005] -- recovery.* span vocabulary is open by design
    _spans.event(f"recovery.{event}", t=t,
                 **{k: v for k, v in fields.items() if v is not None})
    reg = metrics.registry()
    reg.counter("oobleck_recovery_marks_total",
                "RECOVERY_DEADLINE marks emitted").inc(stage=event)
    elapsed = fields.get("elapsed")
    if elapsed is not None:
        _latency_histogram().observe(float(elapsed), stage=event)
    budget = deadline_s()
    if budget is not None and elapsed is not None and elapsed > budget:
        logger.error(
            "%s EXCEEDED: %s took %.1fs against a %.1fs budget (%s)",
            MARK, event, elapsed, budget,
            json.dumps({k: v for k, v in fields.items() if k != "elapsed"},
                       sort_keys=True),
        )
        reg.counter("oobleck_recovery_deadline_breaches_total",
                    "Marks whose elapsed exceeded the budget").inc(
                        stage=event)
        # A breached deadline is the postmortem moment: persist the ring.
        metrics.flight_recorder().dump(f"recovery_deadline_exceeded:{event}")
    return t
