"""Process-wide fence between background XLA work and the train thread.

Root cause of the PR-3 slow-suite flake (tests/elastic/test_multiprocess.py:
a respawned multihost worker died one step after its first post-restore
checkpoint save — loss went NaN then SIGABRT, or SIGSEGV inside the step's
``float(loss)`` readback): after a restore the warm-recovery precompiler
re-arms and starts AOT-compiling predicted stage programs on a daemon
thread, while the train thread is dispatching steps, reading losses back,
and staging checkpoint snapshots to host. On the XLA CPU runtime those
call classes are not reliably safe to interleave — the readback can
observe buffers the concurrent compile's constant-folding evaluator is
touching, and the process dies exactly one step after the save that
re-armed the precompiler. The flake reproduces at PR-2 HEAD and goes
quiet with warm compile caches (nothing left to compile), which is what
pinned the compile thread as the other party.

``device_work(owner)`` is the ordering fence: the precompiler holds it
per chunk lower+compile, the train loop holds it across one step, the
checkpoint path holds it around snapshot staging, and the mirror writer
holds it around its off-thread device_get. Uncontended it is one lock
acquire per step; contended, the wait is bounded by one chunk compile
(the precompiler yields between chunks) and is flight-recorded as
``background_work_wait`` so the trade shows up in incident forensics
instead of disappearing into step time.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

# Flight-record waits longer than this; shorter ones are scheduler noise.
WAIT_RECORD_S = 0.05

# RLock: the train step may re-enter (a step that triggers an inline
# reconfigure can reach the checkpoint staging path while already holding
# the fence).
_lock = threading.RLock()


@contextmanager
def device_work(owner: str):
    """Serialize one unit of XLA-touching work against every other
    holder. `owner` names the party for the flight recorder."""
    t0 = time.perf_counter()
    _lock.acquire()
    waited = time.perf_counter() - t0
    try:
        if waited >= WAIT_RECORD_S:
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "background_work_wait", owner=owner,
                waited_s=round(waited, 4))
        yield
    finally:
        _lock.release()
