"""Fault injection for the elastic control plane (env/config driven).

Recovery claims are only as good as the faults they survived, so the
failure modes the control plane defends against — lost peers, hung
sockets, slow links — are injectable on demand and exercised by tests
(the reference repo injects failures only by SIGKILLing whole agents
from the outside; a hung-but-connected peer is not reproducible that
way).

One env var, ``OOBLECK_CHAOS``, holds a comma-separated list of
directives; each directive is ``action=arg[:qual][@ip]``:

    delay_send=0.25             sleep 0.25 s before every control message
    delay_send=0.25:ping        ... only before PING messages
    drop_send=ping              drop every PING before it hits the wire
    drop_send=ping:3            drop only the 3rd PING
    stall_heartbeat=2@10.0.0.1  agent 10.0.0.1 stops pinging after its
                                2nd ping, socket left OPEN (the hung-peer
                                case TCP disconnect detection cannot see)
    kill_at=step_end:3@10.0.0.1 SIGKILL the process at the 3rd hit of the
                                named barrier, on that host only
    delay_at=serve_reload:0.5   sleep 0.5 s at every hit of the named
                                barrier (slow-I/O injection: a reload
                                crawling on cold storage, an NFS stall)
    kill_stage=1:0              stage-addressed kill: declare the host
                                owning STAGE 1 of pipeline REPLICA 0
                                lost, once, at the next step boundary —
                                the deterministic "kill one DP peer of a
                                specific stage" fault the degraded-mode
                                tests need (single-controller: the engine
                                synthesizes the host loss in place of an
                                out-of-band SIGKILL)
    flap_host=10.0.0.1:2        churn: host 10.0.0.1 flaps — its agent
                                drops the master connection every 2 s and
                                re-registers, repeatedly (the policy
                                plane's quarantine-with-hysteresis case)
    kill_hosts=10.0.0.1+10.0.0.2  correlated simultaneous failure: both
                                hosts declared lost in the SAME step
                                boundary, once (rerouting around two
                                losses at once is usually infeasible —
                                the policy plane must see them together)
    preempt_notice=5:1@10.0.0.1 spot preemption with advance warning:
                                1 s after startup host 10.0.0.1 sends a
                                SIGTERM-style notice to the master, then
                                dies for real 5 s later — the window the
                                proactive drain + checkpoint flush must
                                fit inside
    join_host=10.0.0.5          capacity arrival: host 10.0.0.5 JOINs the
                                running job at the next step boundary,
                                once. The joiner has no process yet, so
                                for THIS action the ``@`` segment is a
                                step-boundary delay, not a process
                                filter: ``join_host=10.0.0.5@3`` arrives
                                after 3 step polls (deterministic — the
                                engine polls once per step)
    join_hosts=10.0.0.5+10.0.0.6  correlated capacity arrival: both hosts
                                JOIN in the SAME step boundary, once —
                                the near-simultaneous-arrival case the
                                master's grow batching window exists for
    spot_lifetime=10.0.0.5:30   the arriving host is a spot instance
                                expected to live ~30 s: the policy plane
                                reads this (NON-consuming) as the
                                amortization horizon when scoring the
                                grow arms, and the engine arms a deferred
                                synthetic loss of that host 30 s after it
                                is admitted — arrival followed by churn
    kill_master=5               control-plane fault: the MASTER process
                                SIGKILLs itself 5 s after startup — the
                                outage the durable journal + agent
                                masterless mode exist for. With a qual,
                                ``kill_master=5:3`` advises the harness
                                to restart the master 3 s after the kill
                                (the master cannot restart itself; the
                                bench/test harness reads the qual)
    partition_master=10.0.0.1:8 network partition: agent 10.0.0.1 loses
                                its master link for 8 s — the master
                                stays up and evicts the host on heartbeat
                                deadline, the agent rides it out
                                masterless and REATTACHes when the
                                partition heals (stale-membership
                                reconcile, not a restart)
    slow_host=10.0.0.1:2.5      gray failure: host 10.0.0.1 runs every
                                step 2.5x slower (its worker sleeps the
                                extra wall time after each step) but
                                stays alive and heartbeating — the
                                straggler the fleet-health detector must
                                flag from telemetry, since no liveness
                                signal ever fires. Like join_host, the
                                ``@`` segment is a step-boundary delay:
                                ``slow_host=10.0.0.1:2.5@3`` starts
                                slowing on the 4th step poll (a healthy
                                baseline first, then degradation)
    kill_replica=8001           serving-replica death: the replica whose
                                HTTP server listens on port 8001 dies at
                                its next /v1/generate request — the
                                in-flight connection aborts with no
                                response and the port stops accepting.
                                ``kill_replica=8001@3`` dies at its 3rd
                                request instead (deterministic mid-
                                traffic kill for router failover tests).
                                One-shot: a dead replica cannot die again
    hang_replica=8001:2         serving-replica hang: the replica on port
                                8001 sleeps 2 s before answering its next
                                request — alive-but-unresponsive, the
                                case the router's liveness probes must
                                flag without any TCP disconnect. One-shot
    traffic_wave=40:20          serve traffic wave: the open-loop load
                                generator ramps its request rate in a
                                triangle wave peaking at 40 req/s with a
                                20 s period — the injectable diurnal peak
                                that drives pool borrow/return cycles
                                without a real client fleet. Like
                                join_host, the ``@`` segment is a poll
                                delay: ``traffic_wave=40:20@3`` stays at
                                baseline for 3 polls first. NON-consuming
                                after activation; activation is
                                flight-recorded once
    spec_misdraft=0.5           speculative-decode fault: each draft
                                token the serve-plane drafter proposes
                                is replaced with a deliberately wrong
                                one with probability 0.5 — acceptance
                                collapses and the verify/rollback path
                                runs hot, but the OUTPUT must stay
                                byte-identical (greedy acceptance
                                discards the junk, rollback rewinds its
                                KV). ``spec_misdraft=0.5@3`` poisons
                                only requests from admission ordinal 3
                                on. NON-consuming after activation;
                                activation is flight-recorded once

Barriers are explicit calls (``chaos().barrier("step_end", ip=...)``)
placed at recovery-relevant points: worker start, step start/end, and
``ckpt_mid_write`` — between the checkpoint writer's shard-data rename
and its manifest write (ckpt/writer.py), the exact window where a kill
leaves a torn checkpoint the restore path must quarantine. The ``@ip``
filter selects a victim in a cluster whose processes share one
environment; directives without ``@ip`` match every process.

Inactive chaos (no env var) costs one attribute read per hook — the
layer is safe to leave compiled into production paths.
"""

from __future__ import annotations

import logging
import os
import signal
import time
from dataclasses import dataclass

logger = logging.getLogger("oobleck.chaos")

ENV_VAR = "OOBLECK_CHAOS"

_KNOWN_ACTIONS = ("delay_send", "drop_send", "stall_heartbeat", "kill_at",
                  "delay_at", "kill_stage", "flap_host", "kill_hosts",
                  "preempt_notice", "join_host", "join_hosts",
                  "spot_lifetime", "kill_master", "partition_master",
                  "slow_host", "traffic_wave", "kill_replica",
                  "hang_replica", "spec_misdraft")


@dataclass
class Rule:
    action: str           # one of _KNOWN_ACTIONS
    arg: str              # seconds / message kind / barrier name / count
    qual: str | None      # ordinal (drop/kill) or kind filter (delay)
    ip: str | None        # restrict to processes reporting this host ip

    def matches_ip(self, ip: str | None) -> bool:
        return self.ip is None or self.ip == ip

    @property
    def nth(self) -> int | None:
        return int(self.qual) if self.qual else None


def parse_spec(spec: str) -> list[Rule]:
    rules: list[Rule] = []
    for directive in spec.split(","):
        directive = directive.strip()
        if not directive:
            continue
        action, sep, payload = directive.partition("=")
        if not sep or action not in _KNOWN_ACTIONS:
            raise ValueError(
                f"bad chaos directive {directive!r}: want "
                f"action=arg[:qual][@ip] with action in {_KNOWN_ACTIONS}"
            )
        payload, _, ip = payload.partition("@")
        arg, _, qual = payload.partition(":")
        rule = Rule(action=action, arg=arg, qual=qual or None, ip=ip or None)
        # Validate eagerly: a typo'd injection spec must fail the test run
        # at parse time, not silently inject nothing.
        if action == "delay_send":
            float(rule.arg)
        elif action == "delay_at":
            float(rule.qual or 0)  # delay_at=<barrier>:<seconds>
        elif action == "stall_heartbeat":
            int(rule.arg or 0)
        elif action == "kill_stage":
            int(rule.arg)           # kill_stage=<stage>:<replica>
            int(rule.qual or 0)
        elif action == "flap_host":
            if not rule.arg:        # flap_host=<ip>:<period>
                raise ValueError(f"flap_host needs a host ip: {directive!r}")
            if float(rule.qual or 0) <= 0:
                raise ValueError(
                    f"flap_host needs a positive period: {directive!r}")
        elif action == "kill_hosts":
            if not all(p for p in rule.arg.split("+")) or not rule.arg:
                raise ValueError(
                    f"kill_hosts needs '+'-joined host ips: {directive!r}")
        elif action == "preempt_notice":
            if float(rule.arg) <= 0:  # preempt_notice=<secs>[:<delay>]@ip
                raise ValueError(
                    f"preempt_notice needs positive seconds: {directive!r}")
            float(rule.qual or 0)
            if not rule.ip:
                raise ValueError(
                    f"preempt_notice needs a victim @ip: {directive!r}")
        elif action == "join_host":
            if not rule.arg:        # join_host=<ip>[@<step-delay>]
                raise ValueError(
                    f"join_host needs a joining ip: {directive!r}")
            int(rule.ip or 0)       # @segment = step-boundary delay
        elif action == "join_hosts":
            if not rule.arg or not all(p for p in rule.arg.split("+")):
                raise ValueError(
                    f"join_hosts needs '+'-joined host ips: {directive!r}")
            int(rule.ip or 0)
        elif action == "spot_lifetime":
            if not rule.arg:        # spot_lifetime=<ip>:<secs>
                raise ValueError(
                    f"spot_lifetime needs a host ip: {directive!r}")
            if float(rule.qual or 0) <= 0:
                raise ValueError(
                    f"spot_lifetime needs positive seconds: {directive!r}")
        elif action == "kill_master":
            if float(rule.arg) <= 0:  # kill_master=<after_s>[:<restart_s>]
                raise ValueError(
                    f"kill_master needs positive seconds: {directive!r}")
            float(rule.qual or 0)
        elif action == "partition_master":
            if not rule.arg:        # partition_master=<ip>:<secs>
                raise ValueError(
                    f"partition_master needs an agent ip: {directive!r}")
            if float(rule.qual or 0) <= 0:
                raise ValueError(
                    f"partition_master needs positive seconds: {directive!r}")
        elif action == "slow_host":
            if not rule.arg:        # slow_host=<ip>:<factor>[@<step>]
                raise ValueError(
                    f"slow_host needs a victim ip: {directive!r}")
            if float(rule.qual or 0) <= 1.0:
                raise ValueError(
                    f"slow_host needs a factor > 1.0: {directive!r}")
            int(rule.ip or 0)       # @segment = step-boundary delay
        elif action == "traffic_wave":
            if float(rule.arg) <= 0:  # traffic_wave=<peak_rps>:<period_s>[@<poll>]
                raise ValueError(
                    f"traffic_wave needs a positive peak rps: {directive!r}")
            if float(rule.qual or 0) <= 0:
                raise ValueError(
                    f"traffic_wave needs a positive period: {directive!r}")
            int(rule.ip or 0)       # @segment = poll delay
        elif action == "kill_replica":
            if int(rule.arg) <= 0:  # kill_replica=<port>[@<req>]
                raise ValueError(
                    f"kill_replica needs a replica port: {directive!r}")
            if int(rule.ip or 1) < 1:  # @segment = request ordinal
                raise ValueError(
                    f"kill_replica ordinal must be >= 1: {directive!r}")
        elif action == "hang_replica":
            if int(rule.arg) <= 0:  # hang_replica=<port>:<secs>
                raise ValueError(
                    f"hang_replica needs a replica port: {directive!r}")
            if float(rule.qual or 0) <= 0:
                raise ValueError(
                    f"hang_replica needs positive seconds: {directive!r}")
        elif action == "spec_misdraft":
            rate = float(rule.arg)  # spec_misdraft=<rate>[@<req>]
            if not 0.0 < rate <= 1.0:
                raise ValueError(
                    f"spec_misdraft rate must be in (0, 1]: {directive!r}")
            if int(rule.ip or 1) < 1:  # @segment = request ordinal
                raise ValueError(
                    f"spec_misdraft ordinal must be >= 1: {directive!r}")
        elif rule.qual is not None:
            int(rule.qual)
        rules.append(rule)
    return rules


class Chaos:
    """Parsed chaos directives + per-rule event counters for one process."""

    def __init__(self, spec: str | None = None):
        if spec is None:
            spec = os.environ.get(ENV_VAR, "")
        self.rules = parse_spec(spec)
        self.active = bool(self.rules)
        self._counts: dict[int, int] = {}

    def _count(self, rule: Rule) -> int:
        i = self.rules.index(rule)
        self._counts[i] = self._counts.get(i, 0) + 1
        return self._counts[i]

    # -- control-plane message hooks (wired into message.send_msg) ------- #

    def send_delay(self, kind: str) -> float:
        """Seconds to sleep before sending a message of `kind`."""
        return sum(
            float(r.arg) for r in self.rules
            if r.action == "delay_send" and r.qual in (None, kind)
        )

    def drop_send(self, kind: str) -> bool:
        """Whether to silently drop a message of `kind` (counts events)."""
        for r in self.rules:
            if r.action == "drop_send" and r.arg == kind:
                n = self._count(r)
                if r.nth is None or n == r.nth:
                    logger.warning("chaos: dropping %s message", kind)
                    from oobleck_tpu.utils import metrics

                    metrics.flight_recorder().record(
                        "chaos_injection", action="drop_send", kind=kind,
                        hit=n)
                    return True
        return False

    # -- heartbeat stall -------------------------------------------------- #

    def heartbeat_stalled(self, ip: str | None) -> bool:
        """True once this process's heartbeat should go silent. The socket
        stays open — only the periodic traffic stops, which is exactly the
        failure mode a `timeout=None` read never detects."""
        for r in self.rules:
            if r.action == "stall_heartbeat" and r.matches_ip(ip):
                if self._count(r) > int(r.arg or 0):
                    return True
        return False

    # -- stage-addressed kill ---------------------------------------------- #

    def kill_stage_target(self) -> tuple[int, int] | None:
        """One-shot (stage, replica) of a pending stage-addressed kill,
        or None. Consuming: each kill_stage rule fires exactly once — the
        injected failure kills the host, and a dead host cannot die again.
        The caller (the engine's step loop) resolves which host owns that
        stage and synthesizes the loss."""
        for r in self.rules:
            if r.action != "kill_stage":
                continue
            i = self.rules.index(r)
            if self._counts.get(i, 0):
                continue
            self._counts[i] = 1
            stage, replica = int(r.arg), int(r.qual or 0)
            logger.warning(
                "chaos: stage-addressed kill of stage %d replica %d",
                stage, replica)
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="kill_stage", stage=stage,
                replica=replica)
            return stage, replica
        return None

    # -- churn directives (policy-plane faults) ----------------------------- #

    def flap_period(self, ip: str | None) -> float | None:
        """Seconds between connection flaps for this host, or None if no
        flap_host rule targets it. The agent owns the flap loop; this is
        read once at startup (flight-recorded on first read only)."""
        for r in self.rules:
            if r.action == "flap_host" and r.arg == ip:
                period = float(r.qual or 0)
                i = self.rules.index(r)
                if not self._counts.get(i):
                    self._counts[i] = 1
                    logger.warning(
                        "chaos: host %s will flap every %.2fs", ip, period)
                    from oobleck_tpu.utils import metrics

                    metrics.flight_recorder().record(
                        "chaos_injection", action="flap_host", ip=ip,
                        period=period)
                return period
        return None

    def kill_hosts_target(self) -> list[str] | None:
        """One-shot list of hosts to declare lost in the SAME step boundary
        (correlated failure), or None. Consuming, like kill_stage_target:
        dead hosts cannot die again."""
        for r in self.rules:
            if r.action != "kill_hosts":
                continue
            i = self.rules.index(r)
            if self._counts.get(i, 0):
                continue
            self._counts[i] = 1
            ips = [p for p in r.arg.split("+") if p]
            logger.warning("chaos: correlated kill of hosts %s", ips)
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="kill_hosts", ips=ips)
            return ips
        return None

    def preempt_notice(self, ip: str | None) -> tuple[float, float] | None:
        """One-shot (warn_seconds, startup_delay_seconds) if this host has a
        pending spot-preemption injection, else None. The agent sends the
        advance notice after the startup delay, then dies warn_seconds
        later — the window proactive drain + checkpoint flush must fit
        inside. Consuming."""
        for r in self.rules:
            if r.action != "preempt_notice" or not r.matches_ip(ip):
                continue
            i = self.rules.index(r)
            if self._counts.get(i, 0):
                continue
            self._counts[i] = 1
            warn, delay = float(r.arg), float(r.qual or 0)
            logger.warning(
                "chaos: preemption notice on %s in %.2fs, death %.2fs later",
                ip, delay, warn)
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="preempt_notice", ip=ip,
                warn_seconds=warn, delay_seconds=delay)
            return warn, delay
        return None

    # -- capacity arrivals (grow-plane faults) ------------------------------ #

    def join_targets(self) -> list[str] | None:
        """One-shot list of hosts ARRIVING at this step boundary, or None.

        The engine polls once per step; a join_host rule with ``@<delay>``
        fires on poll number delay+1 (deterministic down to the step).
        Several rules maturing at the same poll — or one join_hosts rule —
        return together: a correlated arrival the master-side batching
        window must fold into ONE grow incident. Consuming per rule."""
        arrived: list[str] = []
        for r in self.rules:
            if r.action not in ("join_host", "join_hosts"):
                continue
            i = self.rules.index(r)
            n = self._counts.get(i, 0)
            if n < 0:
                continue  # already fired
            delay = int(r.ip or 0)
            if n < delay:
                self._counts[i] = n + 1
                continue
            self._counts[i] = -1
            arrived.extend(p for p in r.arg.split("+") if p)
        if not arrived:
            return None
        logger.warning("chaos: hosts %s arriving (JOIN)", arrived)
        from oobleck_tpu.utils import metrics

        metrics.flight_recorder().record(
            "chaos_injection", action="join_host", ips=arrived)
        return arrived

    def spot_lifetime(self, ip: str | None) -> float | None:
        """Expected lifetime (seconds) of arriving spot host `ip`, or None
        when no spot_lifetime rule names it. NON-consuming: the policy
        scorer reads it per decision as the amortization horizon, and the
        engine reads it once more when admitting the host to arm the
        deferred synthetic loss."""
        for r in self.rules:
            if r.action == "spot_lifetime" and r.arg == ip:
                return float(r.qual or 0)
        return None

    # -- control-plane outage faults --------------------------------------- #

    def kill_master_after(self) -> tuple[float, float | None] | None:
        """One-shot (kill_after_s, restart_after_s|None) if a kill_master
        rule is pending, else None. The MASTER reads this at startup and
        schedules its own SIGKILL; restart_after_s is advisory — the
        master cannot restart itself, so the bench/test harness reads the
        same rule (non-consumed, different process) to time the restart.
        Consuming within a process: a master only dies once."""
        for r in self.rules:
            if r.action != "kill_master":
                continue
            i = self.rules.index(r)
            if self._counts.get(i, 0):
                continue
            self._counts[i] = 1
            after = float(r.arg)
            restart = float(r.qual) if r.qual else None
            logger.warning(
                "chaos: master will SIGKILL itself in %.2fs%s", after,
                f" (harness restart advised after {restart:.2f}s)"
                if restart is not None else "")
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="kill_master",
                after_seconds=after, restart_seconds=restart)
            return after, restart
        return None

    def partition_master_secs(self, ip: str | None) -> float | None:
        """One-shot partition length (seconds) for agent `ip`, or None when
        no partition_master rule names it. The agent severs its master
        link and suppresses redial for that long — the masterless-mode
        fault where the master never died. Consuming."""
        for r in self.rules:
            if r.action != "partition_master" or r.arg != ip:
                continue
            i = self.rules.index(r)
            if self._counts.get(i, 0):
                continue
            self._counts[i] = 1
            return float(r.qual or 0)
        return None

    # -- gray failure (straggler fault) ------------------------------------- #

    def slow_factor(self, ip: str | None) -> float | None:
        """Per-step slowdown factor for host `ip` once its slow_host rule
        has activated, else None. The engine polls once per step; a rule
        with ``@<step>`` activates on poll number step+1 (deterministic,
        like join_targets). NON-consuming after activation — a gray-
        failing host stays slow until something drains it; the activation
        is flight-recorded once."""
        for r in self.rules:
            if r.action != "slow_host" or r.arg != ip:
                continue
            i = self.rules.index(r)
            n = self._counts.get(i, 0)
            if n >= 0:
                delay = int(r.ip or 0)
                if n < delay:
                    self._counts[i] = n + 1
                    return None
                self._counts[i] = -1  # active from here on
                factor = float(r.qual or 0)
                logger.warning(
                    "chaos: host %s now runs %.2fx slow (gray failure)",
                    ip, factor)
                from oobleck_tpu.utils import metrics

                metrics.flight_recorder().record(
                    "chaos_injection", action="slow_host", ip=ip,
                    factor=factor)
            return float(r.qual or 0)
        return None

    # -- serve traffic wave (pool-plane fault) ------------------------------ #

    def traffic_wave(self) -> tuple[float, float] | None:
        """(peak_rps, period_s) of the injected serve traffic wave once its
        rule has activated, else None. The load generator polls once per
        tick; a rule with ``@<poll>`` activates on poll number poll+1
        (deterministic, like slow_factor). NON-consuming after activation
        — the wave keeps oscillating until the run ends; the activation is
        flight-recorded once."""
        for r in self.rules:
            if r.action != "traffic_wave":
                continue
            i = self.rules.index(r)
            n = self._counts.get(i, 0)
            if n >= 0:
                delay = int(r.ip or 0)
                if n < delay:
                    self._counts[i] = n + 1
                    return None
                self._counts[i] = -1  # active from here on
                peak, period = float(r.arg), float(r.qual or 0)
                logger.warning(
                    "chaos: serve traffic wave active (peak %.1f rps, "
                    "period %.1fs)", peak, period)
                from oobleck_tpu.utils import metrics

                metrics.flight_recorder().record(
                    "chaos_injection", action="traffic_wave",
                    peak_rps=peak, period_s=period)
            return float(r.arg), float(r.qual or 0)
        return None

    # -- serving-replica faults (router-plane) ------------------------------ #

    def kill_replica_now(self, port: int) -> bool:
        """True exactly once, on the request whose ordinal a kill_replica
        rule for this port names (first request when no ``@<req>``): the
        replica's HTTP server dies mid-request — the in-flight connection
        aborts with no response and the port stops accepting, which is
        the failover the router must absorb. Call per /v1/generate
        request; counts requests per rule; consuming (a dead replica
        cannot die again)."""
        for r in self.rules:
            if r.action != "kill_replica" or int(r.arg) != int(port):
                continue
            i = self.rules.index(r)
            n = self._counts.get(i, 0)
            if n < 0:
                continue  # already fired
            n += 1
            ordinal = int(r.ip or 1)
            if n < ordinal:
                self._counts[i] = n
                continue
            self._counts[i] = -1
            logger.warning("chaos: killing replica :%d at request %d",
                           int(port), n)
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="kill_replica", port=int(port),
                request=n)
            return True
        return False

    def hang_replica_secs(self, port: int) -> float | None:
        """One-shot hang length (seconds) for the serving replica on
        `port`, or None. The replica's handler sleeps that long before
        answering — the alive-but-unresponsive replica a liveness probe
        must flag without a TCP disconnect ever firing. Consuming."""
        for r in self.rules:
            if r.action != "hang_replica" or int(r.arg) != int(port):
                continue
            i = self.rules.index(r)
            if self._counts.get(i, 0):
                continue
            self._counts[i] = 1
            secs = float(r.qual or 0)
            logger.warning("chaos: hanging replica :%d for %.2fs",
                           int(port), secs)
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="hang_replica", port=int(port),
                seconds=secs)
            return secs
        return None

    # -- speculative-decode faults (serve hot path) ------------------------- #

    def spec_misdraft_rate(self, request_ordinal: int = 1) -> float | None:
        """Probability each DRAFT token is replaced with a deliberately
        wrong one, once a spec_misdraft rule applies to this request —
        else None. ``@<req>`` restricts the fault to requests with
        admission ordinal >= req (default 1 = every request), so a run
        can serve clean traffic first and then misdraft. NON-consuming
        after activation — every subsequent draft stays poisoned (the
        rollback path must hold up under sustained rejection, not one
        bad step); the activation is flight-recorded once. Correctness
        must be unaffected: greedy acceptance discards the wrong tokens
        and rollback rewinds their KV, so the OUTPUT stays byte-identical
        — only acceptance-rate/goodput metrics should move."""
        for r in self.rules:
            if r.action != "spec_misdraft":
                continue
            if int(request_ordinal) < int(r.ip or 1):
                return None
            i = self.rules.index(r)
            rate = float(r.arg)
            if self._counts.get(i, 0) >= 0:
                self._counts[i] = -1  # active from here on
                logger.warning(
                    "chaos: misdrafting %.0f%% of speculative draft tokens "
                    "from request %d", rate * 100.0, int(request_ordinal))
                from oobleck_tpu.utils import metrics

                metrics.flight_recorder().record(
                    "chaos_injection", action="spec_misdraft", rate=rate,
                    request=int(request_ordinal))
            return rate
        return None

    # -- named barriers ---------------------------------------------------- #

    def barrier_delay(self, name: str, ip: str | None = None) -> float:
        """Seconds a matching delay_at rule injects at this barrier (the
        caller sleeps — slow-reload / slow-I/O fault). Counts events."""
        total = 0.0
        for r in self.rules:
            if r.action == "delay_at" and r.arg == name and r.matches_ip(ip):
                total += float(r.qual or 0)
        if total > 0:
            logger.warning("chaos: delaying %.3fs at barrier %s", total, name)
            from oobleck_tpu.utils import metrics

            metrics.flight_recorder().record(
                "chaos_injection", action="delay_at", barrier=name,
                seconds=total)
        return total

    def barrier(self, name: str, ip: str | None = None) -> None:
        """Hit a named barrier; a matching kill_at rule SIGKILLs the process
        (no cleanup, no atexit — the honest worker-crash fault). Matching
        delay_at rules sleep here before any kill check."""
        delay = self.barrier_delay(name, ip)
        if delay > 0:
            time.sleep(delay)
        for r in self.rules:
            if r.action != "kill_at" or r.arg != name or not r.matches_ip(ip):
                continue
            n = self._count(r)
            if r.nth is None or n == r.nth:
                logger.warning(
                    "chaos: killing worker at barrier %s (hit %d, pid %d)",
                    name, n, os.getpid(),
                )
                # Persist the victim's flight recorder while we still can:
                # SIGKILL leaves no other trace of the injection in the
                # postmortem artifacts.
                from oobleck_tpu.utils import metrics

                metrics.flight_recorder().record(
                    "chaos_injection", action="kill_at", barrier=name,
                    hit=n, ip=ip, pid=os.getpid())
                metrics.flight_recorder().dump(f"chaos_kill_at:{name}")
                logging.shutdown()
                os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(60)  # SIGKILL delivery is async; never proceed


_instance: Chaos | None = None


def chaos() -> Chaos:
    """Process-global chaos config, parsed from OOBLECK_CHAOS on first use."""
    global _instance
    if _instance is None:
        _instance = Chaos()
    return _instance


def reset(spec: str | None = None) -> Chaos:
    """Re-parse (tests monkeypatch the env then call this)."""
    global _instance
    _instance = Chaos(spec)
    return _instance
