"""Utilities: timers, logging."""
