"""Step timing.

Capability match for the reference's measure_time decorator around
deepspeed's SynchronizedWallClockTimer (/root/reference/oobleck/utils/
timer.py:8-21): wall-clock accumulation per named region, reported by the
engine every 10 steps. No deepspeed here — a plain monotonic-clock
accumulator; device-side sync is the caller's readback (see
profiler._sync / SKILL.md note on the axon relay).

Thread-safe: the step path mutates the accumulators from the training
thread while ``sync_timers()`` reads them from logging/metrics paths (and
the live-mirror writer runs off-thread), so every access goes through one
module lock and readers get copies. Each observation is also routed into
the metrics registry (``oobleck_timer_seconds{region=...}``) so timer
regions appear in /metrics alongside the engine gauges.
"""

from __future__ import annotations

import functools
import threading
import time
from collections import defaultdict
from dataclasses import dataclass

from oobleck_tpu.utils import metrics


@dataclass
class TimerStats:
    count: int = 0
    total_s: float = 0.0
    last_s: float = 0.0

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def __repr__(self) -> str:
        return (f"TimerStats(n={self.count}, last={self.last_s*1e3:.1f}ms, "
                f"mean={self.mean_s*1e3:.1f}ms)")

    def copy(self) -> "TimerStats":
        return TimerStats(self.count, self.total_s, self.last_s)


_lock = threading.Lock()
_timers: dict[str, TimerStats] = defaultdict(TimerStats)


def _histogram() -> metrics.Histogram:
    return metrics.registry().histogram(
        "oobleck_timer_seconds", "Wall time of named engine regions")


def record(name: str, seconds: float) -> None:
    """Record one observation for region `name`."""
    with _lock:
        st = _timers[name]
        st.count += 1
        st.total_s += seconds
        st.last_s = seconds
    _histogram().observe(seconds, region=name)


def measure_time(name: str):
    """Decorator: accumulate wall time of each call under `name`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                record(name, time.perf_counter() - t0)
        return wrapper

    return deco


def sync_timers() -> dict[str, TimerStats]:
    """Copies, not live references: a caller iterating the result must not
    race the step thread's in-place mutation."""
    with _lock:
        return {name: st.copy() for name, st in _timers.items()}


def reset_timers() -> None:
    with _lock:
        _timers.clear()
