"""Step timing.

Capability match for the reference's measure_time decorator around
deepspeed's SynchronizedWallClockTimer (/root/reference/oobleck/utils/
timer.py:8-21): wall-clock accumulation per named region, reported by the
engine every 10 steps. No deepspeed here — a plain monotonic-clock
accumulator; device-side sync is the caller's readback (see
profiler._sync / SKILL.md note on the axon relay).
"""

from __future__ import annotations

import functools
import time
from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class TimerStats:
    count: int = 0
    total_s: float = 0.0
    last_s: float = 0.0

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def __repr__(self) -> str:
        return (f"TimerStats(n={self.count}, last={self.last_s*1e3:.1f}ms, "
                f"mean={self.mean_s*1e3:.1f}ms)")


_timers: dict[str, TimerStats] = defaultdict(TimerStats)


def measure_time(name: str):
    """Decorator: accumulate wall time of each call under `name`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                st = _timers[name]
                st.count += 1
                st.total_s += dt
                st.last_s = dt
        return wrapper

    return deco


def sync_timers() -> dict[str, TimerStats]:
    return dict(_timers)


def reset_timers() -> None:
    _timers.clear()
