"""Seeded adversarial scenario generators: the scenario-diversity fuzzer.

Each generator turns (rng, fleet size, duration) into a sorted list of
ScenarioEvents — host failures (with a pre-drawn repair delay so ALL
randomness lives here, not in the cluster model), spot-preemption
notices, and traffic-demand swings. Events sharing one ``incident_id``
land at the same instant and are decided as one correlated incident
(reroute infeasible, exactly like the live control plane batches them).

Determinism is a hard contract: every draw comes from the explicit
``random.Random(seed)`` passed in — no wall clock, no ambient entropy —
so the same (scenario, seed, hosts, duration) triple always produces the
same event list, byte for byte, which is what makes the SLO report
diffable across PRs.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

# Hosts per rack for correlated-loss scenarios (TPU-pod-slice flavored:
# a rack is the shared failure domain of its power/network feed).
RACK_SIZE = 8


@dataclass(frozen=True)
class ScenarioEvent:
    """One scripted occurrence. kind: "fail" (host dies; rejoins after
    repair_delay_s), "preempt" (spot notice: proactive drain, then the
    host dies), "join" (fresh capacity arrives mid-run; repair_delay_s
    doubles as the advertised spot lifetime, 0 = on-demand), "traffic"
    (demand factor changes), "master_down" (the control plane itself
    dies for repair_delay_s; the fleet keeps training masterless and
    losses inside the window wait for the restarted master's
    reconcile), "slow" (gray failure: the host keeps training but its
    steps stretch by ``factor``; factor 1.0 = recovered), or "serve"
    (shared-pool scenarios: a co-tenant serve group's priced pressure
    changes — ``demand`` carries the SLO debt in seconds, 0 = trough)."""

    t: float
    kind: str
    host: int = -1
    incident_id: int = -1          # same id + same t -> correlated batch
    cause: str = ""
    repair_delay_s: float = 0.0    # "join": advertised spot lifetime
    demand: float = 1.0            # "traffic" only
    factor: float = 1.0            # "slow" only: step-time multiplier


@dataclass
class Scenario:
    name: str
    seed: int
    hosts: int
    duration_s: float
    events: list[ScenarioEvent] = field(default_factory=list)


def _exp(rng: random.Random, mean: float) -> float:
    return rng.expovariate(1.0 / mean) if mean > 0 else 0.0


def churn_storm(rng: random.Random, hosts: int, duration_s: float, *,
                mean_interarrival_s: float = 20.0,
                mean_repair_s: float = 120.0) -> list[ScenarioEvent]:
    """Independent host failures with exponential interarrival — the
    sustained-churn regime where the scorer's risk term must eventually
    prefer restore over an endless in-memory recovery cascade."""
    events, t, incident = [], 0.0, 0
    while True:
        t += _exp(rng, mean_interarrival_s)
        if t >= duration_s:
            break
        events.append(ScenarioEvent(
            t=round(t, 6), kind="fail", host=rng.randrange(hosts),
            incident_id=incident, cause="churn",
            repair_delay_s=round(_exp(rng, mean_repair_s), 6)))
        incident += 1
    return events


def correlated_rack_loss(rng: random.Random, hosts: int, duration_s: float, *,
                         racks_lost: int = 2,
                         mean_repair_s: float = 300.0) -> list[ScenarioEvent]:
    """Whole racks fail at once (shared feed): every host of the rack in
    one correlated incident, so reroute is never an option and the policy
    plane must choose between re-instantiation and restore."""
    events = []
    n_racks = max(1, hosts // RACK_SIZE)
    times = sorted(round(rng.uniform(0.0, duration_s), 6)
                   for _ in range(racks_lost))
    for incident, t in enumerate(times):
        rack = rng.randrange(n_racks)
        repair = round(_exp(rng, mean_repair_s), 6)
        for h in range(rack * RACK_SIZE,
                       min((rack + 1) * RACK_SIZE, hosts)):
            events.append(ScenarioEvent(
                t=t, kind="fail", host=h, incident_id=incident,
                cause="rack_loss", repair_delay_s=repair))
    return events


def spot_preemption_wave(rng: random.Random, hosts: int, duration_s: float, *,
                         waves: int = 3, wave_frac: float = 0.1,
                         mean_repair_s: float = 180.0
                         ) -> list[ScenarioEvent]:
    """Capacity-reclaim waves: a slice of the fleet gets preemption
    notices in a burst (proactive drain window before the kill), then
    fresh capacity arrives after the repair delay."""
    events, incident = [], 0
    per_wave = max(1, int(hosts * wave_frac))
    for w in range(waves):
        base = round(rng.uniform(0.0, duration_s * 0.9), 6)
        victims = rng.sample(range(hosts), min(per_wave, hosts))
        for h in victims:
            events.append(ScenarioEvent(
                t=round(base + rng.uniform(0.0, 2.0), 6), kind="preempt",
                host=h, incident_id=incident, cause="preemption",
                repair_delay_s=round(_exp(rng, mean_repair_s), 6)))
            incident += 1
    return events


def flap_sequence(rng: random.Random, hosts: int, duration_s: float, *,
                  flappers: int = 2, flaps: int = 5,
                  mean_period_s: float = 15.0) -> list[ScenarioEvent]:
    """A few hosts failing on a short period — the flap detector's diet.
    Repairs return fast (that is what makes a flapper: it comes back and
    fails again), so quarantine hysteresis is what must end the cycle."""
    events, incident = [], 0
    for f in range(min(flappers, hosts)):
        host = rng.randrange(hosts)
        t = round(rng.uniform(0.0, duration_s * 0.2), 6)
        for _ in range(flaps):
            gap = _exp(rng, mean_period_s)
            repair = round(min(gap * 0.5, 10.0), 6)
            events.append(ScenarioEvent(
                t=round(t, 6), kind="fail", host=host,
                incident_id=incident, cause="flap",
                repair_delay_s=repair))
            incident += 1
            t += gap
            if t >= duration_s:
                break
    return events


def diurnal_traffic(rng: random.Random, hosts: int, duration_s: float, *,
                    period_s: float = 600.0, swing: float = 0.5,
                    mean_interarrival_s: float = 60.0,
                    mean_repair_s: float = 120.0) -> list[ScenarioEvent]:
    """Background churn under a day/night demand swing: demand steps
    through a piecewise-sinusoid (precomputed table — no trig drift) so
    goodput-vs-demand is what the SLO report integrates."""
    events = churn_storm(rng, hosts, duration_s,
                         mean_interarrival_s=mean_interarrival_s,
                         mean_repair_s=mean_repair_s)
    # 8 steps per period, triangle-ish: 1-swing .. 1.0 and back.
    steps = [1.0 - swing * abs(1.0 - i / 4.0) for i in range(8)]
    t, i = 0.0, 0
    while t < duration_s:
        events.append(ScenarioEvent(
            t=round(t, 6), kind="traffic",
            demand=round(steps[i % len(steps)], 6)))
        t += period_s / len(steps)
        i += 1
    return events


def capacity_arrival(rng: random.Random, hosts: int, duration_s: float, *,
                     arrivals: int = 6, burst_prob: float = 0.4,
                     spot_frac: float = 0.5,
                     mean_lifetime_s: float = 300.0,
                     mean_interarrival_s: float = 30.0,
                     mean_repair_s: float = 120.0) -> list[ScenarioEvent]:
    """Capacity churn in BOTH directions: background failures (so the
    grow decisions price a real churn regime, not a quiet one) plus fresh
    hosts arriving mid-run — sometimes two in one burst, which the live
    master batches into ONE grow incident and the cluster model must too.
    Each arrival pre-draws whether it is spot (finite advertised
    lifetime; the host dies for good when it expires) or on-demand
    (lifetime 0 = no deadline), so absorb-vs-grow amortization is decided
    against the same signal the live policy plane sees."""
    events = churn_storm(rng, hosts, duration_s,
                         mean_interarrival_s=mean_interarrival_s * 4,
                         mean_repair_s=mean_repair_s)
    incident = 1_000_000  # join incident ids never collide with failures
    next_host, t, made = hosts, 0.0, 0
    while made < arrivals:
        t += _exp(rng, mean_interarrival_s)
        if t >= duration_s:
            break
        burst = 2 if rng.random() < burst_prob else 1
        for _ in range(min(burst, arrivals - made)):
            lifetime = (round(_exp(rng, mean_lifetime_s), 6)
                        if rng.random() < spot_frac else 0.0)
            events.append(ScenarioEvent(
                t=round(t, 6), kind="join", host=next_host,
                incident_id=incident, cause="capacity",
                repair_delay_s=lifetime))
            next_host += 1
            made += 1
        incident += 1
    return events


def master_outage(rng: random.Random, hosts: int, duration_s: float, *,
                  outages: int = 2, mean_outage_s: float = 45.0,
                  min_outage_s: float = 5.0,
                  mean_interarrival_s: float = 40.0,
                  mean_repair_s: float = 120.0) -> list[ScenarioEvent]:
    """Control-plane outages under background churn: the master is down
    for a window while the fleet keeps training masterless. Host failures
    landing INSIDE a window go undetected until the restarted master's
    journal-vs-reality reconcile folds every no-show into ONE batched
    incident (cause=master_outage) — the same deferred-detection shape
    the live reconcile path produces. Arrivals inside a window park and
    re-dial once the master is back."""
    events = churn_storm(rng, hosts, duration_s,
                         mean_interarrival_s=mean_interarrival_s,
                         mean_repair_s=mean_repair_s)
    incident = 2_000_000  # outage incident ids never collide with churn
    for _ in range(outages):
        start = round(rng.uniform(0.0, duration_s * 0.8), 6)
        length = round(max(_exp(rng, mean_outage_s), min_outage_s), 6)
        events.append(ScenarioEvent(
            t=start, kind="master_down", incident_id=incident,
            cause="master_outage", repair_delay_s=length))
        incident += 1
    return events


def straggler(rng: random.Random, hosts: int, duration_s: float, *,
              ramp_steps: int = 6, ramp_interval_s: float = 8.0,
              peak_factor: float = 3.0, sudden_factor: float = 2.5,
              blip_factor: float = 4.0, blip_s: float = 6.0,
              mean_interarrival_s: float = 120.0,
              mean_repair_s: float = 120.0) -> list[ScenarioEvent]:
    """Gray failures: hosts that degrade instead of dying. Three shapes
    under light background churn —

    * a **gradual** straggler ramping to peak_factor over ramp_steps
      stages (a failing NIC / thermal throttle: the detector must catch
      it from relative statistics before it becomes an outage);
    * a **sudden** straggler jumping straight to sudden_factor and
      staying there;
    * a **red-herring blip**: a short severe slowdown that recovers to
      1.0 within blip_s — the persistence gate must NOT raise an
      incident for it.

    Incident ids live in the 3_000_000 band (never collide with churn /
    join / outage ids)."""
    events = churn_storm(rng, hosts, duration_s,
                         mean_interarrival_s=mean_interarrival_s,
                         mean_repair_s=mean_repair_s)
    incident = 3_000_000
    victims = rng.sample(range(hosts), min(3, hosts))
    # Gradual ramp: factor climbs linearly to the peak, then persists.
    t = round(rng.uniform(0.1, duration_s * 0.3), 6)
    for i in range(ramp_steps):
        frac = (i + 1) / ramp_steps
        events.append(ScenarioEvent(
            t=round(t + i * ramp_interval_s, 6), kind="slow",
            host=victims[0], incident_id=incident, cause="gray_gradual",
            factor=round(1.0 + (peak_factor - 1.0) * frac, 6)))
    incident += 1
    # Sudden jump, no recovery.
    if len(victims) > 1:
        events.append(ScenarioEvent(
            t=round(rng.uniform(0.1, duration_s * 0.5), 6), kind="slow",
            host=victims[1], incident_id=incident, cause="gray_sudden",
            factor=round(sudden_factor, 6)))
    incident += 1
    # Red-herring blip: severe but short; back to 1.0 before the
    # persistence gate can fill.
    if len(victims) > 2:
        t_blip = round(rng.uniform(0.1, duration_s * 0.7), 6)
        events.append(ScenarioEvent(
            t=t_blip, kind="slow", host=victims[2],
            incident_id=incident, cause="gray_blip",
            factor=round(blip_factor, 6)))
        events.append(ScenarioEvent(
            t=round(t_blip + blip_s, 6), kind="slow", host=victims[2],
            incident_id=incident, cause="gray_blip", factor=1.0))
    return events


def shared_pool(rng: random.Random, hosts: int, duration_s: float, *,
                period_s: float = 600.0, peak_debt_s: float = 90.0,
                mean_interarrival_s: float = 60.0,
                mean_repair_s: float = 120.0) -> list[ScenarioEvent]:
    """Multi-tenant chip pool: a diurnal serve-pressure wave over
    background training churn. The wave steps through a piecewise
    triangle (trough half at zero — off-peak IS the reclaim signal),
    each step one "serve" event whose ``demand`` carries the priced SLO
    debt in seconds. The cluster model feeds these to the REAL
    PoolArbiter: peak steps become borrow incidents, lease expiry
    mid-peak exercises the re-borrow path, and expiry in the trough
    sends the chips home through the grow path. Incident ids live in
    the 4_000_000 band (never collide with churn/join/outage/straggler
    ids)."""
    events = churn_storm(rng, hosts, duration_s,
                         mean_interarrival_s=mean_interarrival_s,
                         mean_repair_s=mean_repair_s)
    incident = 4_000_000
    # 8 steps per period: a trough half and a triangle to the peak.
    profile = [0.0, 0.0, 0.5, 1.0, 1.0, 0.5, 0.0, 0.0]
    t, i = 0.0, 0
    while t < duration_s:
        events.append(ScenarioEvent(
            t=round(t, 6), kind="serve", incident_id=incident,
            cause="serve_wave",
            demand=round(peak_debt_s * profile[i % len(profile)], 6)))
        incident += 1
        t += period_s / len(profile)
        i += 1
    return events


GENERATORS = {
    "churn_storm": churn_storm,
    "master_outage": master_outage,
    "capacity_arrival": capacity_arrival,
    "correlated_rack_loss": correlated_rack_loss,
    "spot_preemption_wave": spot_preemption_wave,
    "flap_sequence": flap_sequence,
    "diurnal_traffic": diurnal_traffic,
    "straggler": straggler,
    "shared_pool": shared_pool,
}


def make_scenario(name: str, *, seed: int, hosts: int,
                  duration_s: float, **params) -> Scenario:
    """Build one named scenario from an explicit seed. Events are sorted
    by (t, host, kind) — a total order, so heap insertion order (and with
    it the whole run) is reproducible."""
    if name not in GENERATORS:
        raise ValueError(f"unknown scenario {name!r}: "
                         f"want one of {sorted(GENERATORS)}")
    rng = random.Random(seed)
    events = GENERATORS[name](rng, hosts, duration_s, **params)
    events.sort(key=lambda e: (e.t, e.host, e.kind, e.incident_id))
    return Scenario(name=name, seed=seed, hosts=hosts,
                    duration_s=duration_s, events=events)
