"""Trace-replay cluster simulator: what-if fleet planning with no hardware.

The sim plane answers "would this configuration survive that incident
pattern?" offline, at thousands-of-hosts scale, by replaying two kinds of
input against a discrete-event cluster model:

  * the recorded corpus — committed ``incident-*.json`` postmortems,
    ``flight-*.jsonl`` rings, and bench rounds (``corpus.py``) — which
    also feeds ``priors.py``'s fitted per-mechanism latency priors; and
  * synthesized adversarial scenarios — churn storms, correlated rack
    loss, spot-preemption waves, flap sequences, diurnal traffic swings —
    from seeded generators with explicit PRNG state (``scenarios.py``).

The model (``cluster.py``) costs every recovery through the REAL
``degrade.classify`` / ``degrade.planner.plan_reroute`` /
``execution.schedule.replay_schedule`` / ``policy`` code paths — the
simulator cannot drift from the system it models because it has no
recovery model of its own. ``slo.py`` reduces a run to a fleet SLO report
(recovery p99, goodput under churn, decisions-vs-oracle regret) that
``bench.py``'s ``sim`` key records and ``bench --diff`` gates.

Deterministic by construction: same seed + same corpus -> byte-identical
SLO report (no wall clock, no ambient entropy, hermetic metrics
registry). CLI: ``python -m oobleck_tpu.sim`` / ``make sim-bench``.
"""

from __future__ import annotations
