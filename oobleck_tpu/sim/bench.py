"""Simulated-SLO bench: the scenario suite the perf gate runs per PR.

Small-but-real: every scenario family at 64 hosts, plus the
1024-host churn storm the acceptance bar names, plus an in-run
determinism check (the 64-host storm executed twice from fresh state and
byte-compared). CPU-only, jax-free, and bounded well under the tier-1
budget; ``bench.py`` records the output under its ``sim`` key and
``bench --diff`` compares it round-over-round (goodput/agreement up is
good, recovery/regret seconds down is good).

Run as ``python -m oobleck_tpu.sim.bench`` (or ``make sim-bench``).
Prints ONE JSON line on stdout, like every other bench in the repo.
"""

from __future__ import annotations

import json
import time

from oobleck_tpu.sim import slo
from oobleck_tpu.sim.cluster import SimCluster, SimConfig
from oobleck_tpu.sim.scenarios import make_scenario

# (label, scenario, hosts, duration_s, seed, generator params)
SUITE = (
    ("churn_storm_64", "churn_storm", 64, 600.0, 1117, {}),
    ("rack_loss_64", "correlated_rack_loss", 64, 600.0, 1117, {}),
    ("preemption_wave_64", "spot_preemption_wave", 64, 600.0, 1117, {}),
    ("flap_sequence_64", "flap_sequence", 64, 600.0, 1117, {}),
    ("diurnal_traffic_64", "diurnal_traffic", 64, 1800.0, 1117, {}),
    ("capacity_arrival_64", "capacity_arrival", 64, 600.0, 1117, {}),
    ("straggler_64", "straggler", 64, 600.0, 1117, {}),
    ("shared_pool_64", "shared_pool", 64, 1800.0, 1117, {}),
    ("churn_storm_1024", "churn_storm", 1024, 600.0, 1117,
     {"mean_interarrival_s": 4.0}),
)


def _one(label: str, name: str, hosts: int, duration_s: float, seed: int,
         params: dict) -> tuple[dict, str]:
    scenario = make_scenario(name, seed=seed, hosts=hosts,
                             duration_s=duration_s, **params)
    config = SimConfig(hosts=hosts)
    t0 = time.perf_counter()
    report = slo.slo_report(SimCluster(config, scenario).run())
    elapsed = time.perf_counter() - t0
    summary = {
        "incidents": report["incidents"],
        "recovery_p99_s": report["recovery"]["p99_s"],
        "goodput_ratio": report["goodput_ratio"],
        "regret_mean_s": report["regret"]["mean_s"],
        "oracle_agreement": report["regret"]["oracle_agreement"],
        "elapsed_s": round(elapsed, 3),
    }
    return summary, slo.render(report)


def measure() -> dict:
    out: dict = {}
    t0 = time.perf_counter()
    renders: dict[str, str] = {}
    for label, name, hosts, duration_s, seed, params in SUITE:
        out[label], renders[label] = _one(label, name, hosts, duration_s,
                                          seed, params)
    # Determinism gate: the 64-host storm, the straggler scenario (which
    # adds the telemetry-tick event stream + the real detector to the
    # loop), AND the shared-pool scenario (which adds the cross-tenant
    # arbiter + lease-sweep events) again, from fresh state; the
    # canonical renders must match byte for byte.
    _, again = _one("churn_storm_64", *SUITE[0][1:])
    straggler_entry = next(s for s in SUITE if s[0] == "straggler_64")
    _, s_again = _one("straggler_64", *straggler_entry[1:])
    pool_entry = next(s for s in SUITE if s[0] == "shared_pool_64")
    _, p_again = _one("shared_pool_64", *pool_entry[1:])
    out["determinism"] = {
        "scenario": "churn_storm_64+straggler_64+shared_pool_64",
        "byte_identical": (renders["churn_storm_64"] == again
                           and renders["straggler_64"] == s_again
                           and renders["shared_pool_64"] == p_again),
    }
    out["elapsed_s"] = round(time.perf_counter() - t0, 3)
    return out


def main() -> None:
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
