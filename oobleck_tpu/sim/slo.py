"""Fleet SLO reduction: one simulator run -> the numbers a PR is gated on.

Three SLO families, mirroring what the live bench gate measures but at
fleet scale no hardware run could cover:

  * recovery latency percentiles (nearest-rank, so the report is exact
    and deterministic — no interpolation float drift);
  * goodput under churn — the piecewise-integrated delivered/demanded
    ratio from the cluster model;
  * decisions-vs-oracle regret — with hindsight, each incident's realized
    time-to-next-failure is known, so the oracle prices every feasible
    arm with the TRUE amortization window instead of the MTBF estimate
    the policy engine had to use. Regret is how many seconds the chosen
    arm cost over the hindsight-best one; agreement is how often they
    coincided. This is Chameleon's policy-evaluation framing (arxiv
    2508.21613) run entirely offline.

``crossval_report`` closes the loop the other way: it replays a RECORDED
incident (rig shape + calibrated op durations stored in the incident's
attrs) through the same classify/plan/fit code paths and compares the
simulator's projections against what the hardware measured.
"""

from __future__ import annotations

import math

from oobleck_tpu.degrade.classify import classify_failure
from oobleck_tpu.degrade.planner import PipelineSpec, plan_reroute
from oobleck_tpu.policy.scorer import AMORT_CAP_S
from oobleck_tpu.sim.corpus import Corpus
from oobleck_tpu.sim.priors import fit_priors

PERCENTILES = (50, 90, 99)


def _pct(xs: list[float], q: float) -> float | None:
    """Nearest-rank percentile over raw samples (exact, deterministic)."""
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, max(0, math.ceil(q / 100.0 * len(xs)) - 1))
    return xs[i]


def _hindsight_cost(arm: dict, window_s: float) -> float:
    """The scorer's cost formula with the TRUE amortization window and no
    churn-risk hedge — with hindsight there is no risk, only what
    actually happened."""
    return (arm["latency_s"] + arm["lost_work_s"]
            + (1.0 - min(arm["retention"], 1.0))
            * min(window_s, AMORT_CAP_S))


def slo_report(run: dict) -> dict:
    """Reduce one SimCluster.run() record to the gated SLO report."""
    incidents = run["incidents"]
    duration = run["scenario"]["duration_s"]
    recoveries = [i["realized_recovery_s"] for i in incidents]
    mechanisms: dict[str, int] = {}
    prior_sources: set[str] = set()
    for inc in incidents:
        mechanisms[inc["mechanism"]] = mechanisms.get(inc["mechanism"], 0) + 1
        for arm in inc["arms"].values():
            if arm.get("prior_source"):
                prior_sources.add(arm["prior_source"])

    total_regret = 0.0
    agreements = 0
    for i, inc in enumerate(incidents):
        window = (incidents[i + 1]["t"] if i + 1 < len(incidents)
                  else duration) - inc["t"]
        window = max(window, 0.0)
        feasible = {m: a for m, a in inc["arms"].items() if a["feasible"]}
        if not feasible:
            continue
        costs = {m: _hindsight_cost(a, window) for m, a in feasible.items()}
        best = min(sorted(costs), key=lambda m: (costs[m], m))
        chosen = inc["mechanism"]
        if chosen == best:
            agreements += 1
        if chosen in costs:
            total_regret += costs[chosen] - costs[best]

    n = len(incidents)
    report = {
        "scenario": dict(run["scenario"]),
        "config": dict(run["config"]),
        "incidents": n,
        "mechanisms": mechanisms,
        "recovery": {f"p{q}_s": (round(v, 6) if v is not None else None)
                     for q in PERCENTILES
                     for v in [_pct(recoveries, q)]},
        "goodput_ratio": run["goodput_ratio"],
        "lost_work_s": run["lost_work_s"],
        "regret": {
            "total_s": round(total_regret, 6),
            "mean_s": round(total_regret / n, 6) if n else 0.0,
            "oracle_agreement": round(agreements / n, 6) if n else 1.0,
        },
        "prior_sources": sorted(prior_sources),
        "final": dict(run["final"]),
    }
    if "pool" in run:
        # Shared-pool scenarios only: lease traffic + the cross-tenant
        # bill. Absent otherwise, so single-tenant renders are unchanged.
        report["pool"] = dict(run["pool"])
    return report


def render(report: dict) -> str:
    """Canonical serialization: the byte-identical-across-runs contract
    tests and the determinism gate compare THIS string."""
    import json

    return json.dumps(report, sort_keys=True, separators=(",", ":"))


# -- cross-validation against the recorded corpus --------------------------- #

def replay_incident(inc, corpus: Corpus) -> dict | None:
    """Replay one recorded incident through the simulator's costing paths
    and put its projections next to the hardware measurements.

    Needs the rig shape + calibrated op durations the fixture generator
    stores in the incident's attrs; returns None for incidents without
    them (live-production incidents carry marks but not op calibration).
    """
    rig = inc.attrs.get("rig")
    op_list = inc.attrs.get("op_times")
    measured = inc.attrs.get("measured")
    if not (isinstance(rig, dict) and op_list and isinstance(measured, dict)):
        return None
    op_times = {(int(s), int(c), str(k)): (float(total), int(count))
                for s, c, k, total, count in op_list}
    chips = int(rig["chips_per_host"])
    hpp = int(rig["hosts_per_pipeline"])
    stages = hpp * chips
    n_pipes = int(rig["hosts"]) // hpp
    specs = [PipelineSpec(num_stages=stages,
                          num_microbatches=int(
                              rig["microbatches_per_pipeline"]),
                          virtual_stages=int(rig.get("virtual_stages", 1)),
                          op_times=op_times)
             for _ in range(n_pipes)]
    ranks = [[p * hpp * chips + i for i in range(hpp * chips)]
             for p in range(n_pipes)]
    report = classify_failure(int(rig["lost_host"]), ranks, chips)
    plan = plan_reroute(report, specs)

    fitted = fit_priors(corpus)["latency_s"]
    sim = {
        "feasible": plan.feasible,
        "survivor_slowdown": round(plan.slowdown, 6) if plan.feasible
        else None,
        "retention": round(plan.throughput_retention, 6),
        "recovery_s": fitted.get(inc.mechanism or "reroute"),
    }
    out = {"trace_id": inc.trace_id, "mechanism": inc.mechanism,
           "sim": sim, "measured": dict(measured), "rel_err": {}}
    for sim_key, meas_key in (
            ("survivor_slowdown", "survivor_slowdown_measured"),
            ("recovery_s", "recovery_to_next_step_s")):
        s, m = sim.get(sim_key), measured.get(meas_key)
        if isinstance(s, (int, float)) and isinstance(m, (int, float)) \
                and m > 0:
            out["rel_err"][sim_key] = round(abs(s - m) / m, 6)
    return out


def crossval_report(corpus: Corpus) -> dict:
    """Replay every replayable incident in the corpus; the cross-
    validation test gates on every rel_err staying within tolerance."""
    replays = [r for r in (replay_incident(i, corpus)
                           for i in corpus.incidents) if r]
    return {"corpus": corpus.root, "replayable": len(replays),
            "incidents": len(corpus.incidents), "replays": replays}
