"""Corpus loader: recorded traces -> typed events the simulator can replay.

Three sources, one Corpus:

  * ``incident-<n>.json`` — the obs plane's committed postmortems
    (schema-validated: unknown ``schema_version`` is skipped with a
    warning, records missing the core keys are skipped, duplicate
    trace_ids are deduped first-wins);
  * ``flight-*.jsonl`` — dumped flight-recorder rings (one JSON event per
    line; unparseable lines are counted, not fatal);
  * ``BENCH_r*.json`` — driver-committed bench rounds whose ``parsed``
    payload may carry a ``degrade`` section with measured recovery
    latencies.

Beyond replay, the corpus is the policy plane's training set:
``latency_samples()`` extracts per-mechanism measured recovery latencies
(incident ``total_s`` preferred — it is the failure-to-resume metric the
scorer prices; flight ``degrade_decision`` / ``policy_decision_measured``
events and bench rounds fill in incidents the obs plane never committed),
deduped so an incident's embedded flight tail and a separately dumped
ring never double-count one recovery. ``priors.py`` fits
``learned_priors.json`` from exactly these samples.
"""

from __future__ import annotations

import json
import logging
import os
import re
from dataclasses import dataclass, field

from oobleck_tpu.obs.incident import SCHEMA_VERSION, list_incidents
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.sim")

_FLIGHT_RE = re.compile(r"flight-.*\.jsonl$")
_BENCH_RE = re.compile(r"BENCH_r\d+\.json$")

# Keys a parseable incident must carry to be replayable at all.
_REQUIRED_INCIDENT_KEYS = ("trace_id", "lost_ip", "marks")

# Bench-round degrade section -> prior-table mechanism key.
_BENCH_MECHANISMS = (
    ("reroute", "reroute"),
    ("reinstantiate_respawn", "reinstantiate_respawn"),
    ("reinstantiate_inplace", "reinstantiate"),
)


@dataclass
class IncidentEvent:
    """One committed incident, reduced to what replay and fitting need."""

    path: str
    trace_id: str
    schema_version: int
    lost_ip: str
    cause: str
    marks: dict
    total_s: float
    mechanism: str = ""            # "" when no decision event was captured
    measured_recovery_s: float | None = None
    plan: dict = field(default_factory=dict)
    attrs: dict = field(default_factory=dict)
    flight: list = field(default_factory=list)


@dataclass
class FlightEvent:
    """One flight-recorder ring event from a dumped ``flight-*.jsonl``."""

    t: float
    event: str
    fields: dict
    source: str


@dataclass
class BenchRound:
    """One driver-committed bench round (the ``parsed`` payload)."""

    path: str
    round_n: int
    parsed: dict
    degrade: dict = field(default_factory=dict)


@dataclass
class Corpus:
    """Everything loadable under one trace directory, plus what was not."""

    root: str
    incidents: list[IncidentEvent] = field(default_factory=list)
    flight: list[FlightEvent] = field(default_factory=list)
    bench_rounds: list[BenchRound] = field(default_factory=list)
    skipped: list[tuple[str, str]] = field(default_factory=list)

    def latency_samples(self) -> dict[str, list[float]]:
        """mechanism -> measured recovery seconds, one sample per distinct
        recovery across all three sources (see module docstring)."""
        samples: dict[str, list[float]] = {}
        consumed: set = set()

        def add(mechanism: str, seconds) -> None:
            if mechanism and isinstance(seconds, (int, float)) and seconds > 0:
                samples.setdefault(mechanism, []).append(float(seconds))

        for inc in self.incidents:
            for ev in inc.flight:
                if isinstance(ev, dict) and ev.get("event") in (
                        "degrade_decision", "policy_decision",
                        "policy_decision_measured"):
                    consumed.add(_decision_key(ev))
            if inc.mechanism and inc.mechanism != "disabled":
                # total_s (detect -> first post-recovery step) is the
                # failure-to-resume latency; fall back to the decision's
                # own measured reconfigure time when marks are partial.
                add(inc.mechanism, inc.total_s or inc.measured_recovery_s)
        for fe in self.flight:
            key = _decision_key({"event": fe.event, "t": fe.t, **fe.fields})
            if key in consumed:
                continue
            if fe.event in ("degrade_decision", "policy_decision_measured"):
                consumed.add(key)
                add(fe.fields.get("mechanism", ""),
                    fe.fields.get("measured_recovery_s"))
        for rnd in self.bench_rounds:
            for section, mechanism in _BENCH_MECHANISMS:
                sec = rnd.degrade.get(section)
                if isinstance(sec, dict):
                    add(mechanism, sec.get("recovery_to_next_step_s"))
        return samples

    def stats(self) -> dict:
        """Summary block for reports and the CLI."""
        return {
            "incidents": len(self.incidents),
            "flight_events": len(self.flight),
            "bench_rounds": len(self.bench_rounds),
            "skipped": len(self.skipped),
            "latency_samples": {m: len(v)
                                for m, v in self.latency_samples().items()},
        }


def _decision_key(ev: dict) -> tuple:
    """Identity of one recorded decision across ring copies: the same
    event embedded in an incident and dumped in a flight file carries the
    same trace_id/decided_at, whatever file it came from."""
    return (ev.get("event"), ev.get("trace_id"), ev.get("decided_at"),
            ev.get("t"))


def _incident_decision(rec: dict) -> tuple[str, float | None, dict]:
    """(mechanism, measured_recovery_s, plan) from an incident's embedded
    flight tail; policy_decision matching the trace wins over the raw
    degrade_decision (it is the authoritative verdict)."""
    mechanism, measured, plan = "", None, {}
    for ev in rec.get("flight") or ():
        if not isinstance(ev, dict):
            continue
        kind = ev.get("event")
        if kind == "degrade_decision" and not mechanism:
            mechanism = str(ev.get("mechanism") or "")
            measured = ev.get("measured_recovery_s")
            plan = ev.get("plan") or {}
        elif (kind == "policy_decision"
              and ev.get("trace_id") == rec.get("trace_id")):
            mechanism = str(ev.get("mechanism") or "")
    return mechanism, measured, plan


def load_corpus(root: str) -> Corpus:
    """Load every trace under ``root`` into one validated Corpus."""
    corpus = Corpus(root=root)
    reg = metrics.registry()
    events_total = reg.counter(
        "oobleck_sim_corpus_events_total",
        "Corpus records loaded by kind (incident/flight/bench_round)")
    skipped_total = reg.counter(
        "oobleck_sim_corpus_skipped_total",
        "Corpus records skipped at load time, by reason")

    def skip(path: str, reason: str) -> None:
        corpus.skipped.append((path, reason))
        skipped_total.inc(reason=reason)
        logger.warning("sim corpus: skipping %s: %s", path, reason)

    seen_traces: set[str] = set()
    for path, rec in list_incidents(root):
        version = rec.get("schema_version", SCHEMA_VERSION)
        if not isinstance(version, int) or version > SCHEMA_VERSION:
            skip(path, f"unknown_schema_version:{version!r}")
            continue
        if any(k not in rec for k in _REQUIRED_INCIDENT_KEYS):
            skip(path, "missing_required_keys")
            continue
        trace_id = str(rec["trace_id"])
        if trace_id in seen_traces:
            skip(path, "duplicate_trace_id")
            continue
        seen_traces.add(trace_id)
        mechanism, measured, plan = _incident_decision(rec)
        corpus.incidents.append(IncidentEvent(
            path=path,
            trace_id=trace_id,
            schema_version=version,
            lost_ip=str(rec["lost_ip"]),
            cause=str(rec.get("cause") or ""),
            marks=dict(rec.get("marks") or {}),
            total_s=float(rec.get("total_s") or 0.0),
            mechanism=mechanism,
            measured_recovery_s=measured,
            plan=plan,
            attrs=dict(rec.get("attrs") or {}),
            flight=list(rec.get("flight") or ()),
        ))
        events_total.inc(kind="incident")

    try:
        names = sorted(os.listdir(root))
    except OSError:
        names = []
    for name in names:
        path = os.path.join(root, name)
        if _FLIGHT_RE.match(name):
            bad = 0
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            bad += 1
                            continue
                        if not isinstance(ev, dict) or "event" not in ev:
                            bad += 1
                            continue
                        fields = {k: v for k, v in ev.items()
                                  if k not in ("t", "event")}
                        corpus.flight.append(FlightEvent(
                            t=float(ev.get("t") or 0.0),
                            event=str(ev["event"]),
                            fields=fields, source=path))
                        events_total.inc(kind="flight")
            except OSError as e:
                skip(path, f"unreadable:{e.__class__.__name__}")
                continue
            if bad:
                skip(path, f"unparseable_lines:{bad}")
        elif _BENCH_RE.match(name):
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError) as e:
                skip(path, f"unreadable:{e.__class__.__name__}")
                continue
            if not isinstance(rec, dict):
                skip(path, "not_a_dict")
                continue
            parsed = rec.get("parsed") if isinstance(rec.get("parsed"),
                                                     dict) else rec
            degrade = parsed.get("degrade")
            corpus.bench_rounds.append(BenchRound(
                path=path,
                round_n=int(rec.get("n") or 0),
                parsed=parsed,
                degrade=degrade if isinstance(degrade, dict) else {}))
            events_total.inc(kind="bench_round")
    return corpus
