"""Fit the policy scorer's latency priors from the incident corpus.

The shipped ``PRIOR_LATENCY_S`` table is what the scorer believes before
any history exists; this module replaces belief with evidence. Every
committed incident, dumped flight ring, and bench round contributes its
measured failure-to-resume latency (``Corpus.latency_samples``); the fit
is the per-mechanism median — robust to the one 20x outlier a respawn
under load produces, and deterministic (no wall clock in the output, so
re-fitting an unchanged corpus is byte-identical).

The emitted ``learned_priors.json`` is what ``policy.signals`` loads when
``$OOBLECK_POLICY_PRIORS`` (or an engine's ``priors_path``) points at it;
from then on every PolicyDecision's arms carry
``prior_source="learned:<path>"`` instead of ``"hardcoded"``.
"""

from __future__ import annotations

import json
import os

from oobleck_tpu.policy.signals import PRIOR_LATENCY_S, PRIORS_VERSION
from oobleck_tpu.sim.corpus import Corpus

# Only mechanisms the scorer actually prices get fitted entries; anything
# else in the corpus (typos, future mechanisms) is reported, not used.
_KNOWN_MECHANISMS = tuple(sorted(PRIOR_LATENCY_S))


def _median(xs: list[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    mid = n // 2
    return xs[mid] if n % 2 else (xs[mid - 1] + xs[mid]) / 2.0


def fit_priors(corpus: Corpus, *, min_samples: int = 1) -> dict:
    """The ``learned_priors.json`` record: fitted ``latency_s`` for every
    mechanism with at least ``min_samples`` corpus observations (the rest
    keep falling through to the hardcoded table at decision time), plus
    provenance naming exactly what the fit saw."""
    samples = corpus.latency_samples()
    latency: dict[str, float] = {}
    provenance: dict[str, dict] = {}
    for mechanism, xs in sorted(samples.items()):
        prov = {
            "samples": len(xs),
            "median_s": round(_median(xs), 6),
            "mean_s": round(sum(xs) / len(xs), 6),
            "min_s": round(min(xs), 6),
            "max_s": round(max(xs), 6),
        }
        if mechanism not in _KNOWN_MECHANISMS:
            prov["ignored"] = "unknown_mechanism"
        elif len(xs) < min_samples:
            prov["ignored"] = f"fewer_than_{min_samples}_samples"
        else:
            latency[mechanism] = round(_median(xs), 6)
        provenance[mechanism] = prov
    return {
        "version": PRIORS_VERSION,
        "latency_s": latency,
        "provenance": {
            "fitted_from": corpus.root,
            "incidents": len(corpus.incidents),
            "flight_events": len(corpus.flight),
            "bench_rounds": len(corpus.bench_rounds),
            "estimator": "median",
            "mechanisms": provenance,
        },
    }


def write_priors(path: str, priors: dict) -> str:
    """Atomically publish a fitted priors file (tmp + rename, so a reader
    mid-write never sees a torn table)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(priors, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
