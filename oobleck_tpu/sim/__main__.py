"""CLI for the cluster simulator.

    python -m oobleck_tpu.sim run --scenario churn_storm --hosts 1024 \
        --seed 42 --duration-s 600 [--priors learned_priors.json]
    python -m oobleck_tpu.sim fit-priors --corpus $OOBLECK_METRICS_DIR \
        --out learned_priors.json
    python -m oobleck_tpu.sim replay --corpus tests/sim/data/degrade_bench
    python -m oobleck_tpu.sim scenarios

``run`` prints the canonical one-line SLO report (byte-identical for
equal seed + corpus — pipe two runs through ``diff`` to audit it).
``fit-priors`` closes the corpus -> policy loop; point
``$OOBLECK_POLICY_PRIORS`` at the output to activate it. ``replay``
cross-validates the simulator against recorded measurements.
"""

from __future__ import annotations

import argparse
import json
import sys


def _calibrated_op_times(corpus) -> dict:
    """Per-op calibration from the first recorded incident that carries
    one (the degrade-bench fixture does); {} -> the planner's documented
    fwd=1/bwd=2 fallback model."""
    for inc in corpus.incidents:
        op_list = inc.attrs.get("op_times")
        if op_list:
            return {(int(s), int(c), str(k)): (float(total), int(count))
                    for s, c, k, total, count in op_list}
    return {}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m oobleck_tpu.sim",
                                 description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser("run", help="run one scenario, print SLO report")
    runp.add_argument("--scenario", default="churn_storm")
    runp.add_argument("--hosts", type=int, default=64)
    runp.add_argument("--seed", type=int, default=0)
    runp.add_argument("--duration-s", type=float, default=600.0)
    runp.add_argument("--chips-per-host", type=int, default=2)
    runp.add_argument("--hosts-per-pipeline", type=int, default=1)
    runp.add_argument("--microbatches", type=int, default=8,
                      help="microbatches per pipeline replica")
    runp.add_argument("--virtual-stages", type=int, default=1)
    runp.add_argument("--checkpoint-period-s", type=float, default=300.0)
    runp.add_argument("--mode", default="adaptive",
                      help="policy mode (adaptive|reroute|...)")
    runp.add_argument("--corpus", default=None,
                      help="trace dir for op-duration calibration")
    runp.add_argument("--priors", default=None,
                      help="learned_priors.json to decide with")

    fitp = sub.add_parser("fit-priors",
                          help="fit latency priors from a trace corpus")
    fitp.add_argument("--corpus", required=True)
    fitp.add_argument("--out", required=True)
    fitp.add_argument("--min-samples", type=int, default=1)

    repp = sub.add_parser("replay",
                          help="cross-validate sim vs recorded incidents")
    repp.add_argument("--corpus", required=True)

    sub.add_parser("scenarios", help="list scenario generators")

    args = ap.parse_args(argv)

    from oobleck_tpu.sim import corpus as corpus_mod
    from oobleck_tpu.sim import priors as priors_mod
    from oobleck_tpu.sim import slo
    from oobleck_tpu.sim.cluster import SimCluster, SimConfig
    from oobleck_tpu.sim.scenarios import GENERATORS, make_scenario

    if args.cmd == "scenarios":
        print(json.dumps(sorted(GENERATORS)))
        return 0

    if args.cmd == "fit-priors":
        corpus = corpus_mod.load_corpus(args.corpus)
        priors = priors_mod.fit_priors(corpus,
                                       min_samples=args.min_samples)
        priors_mod.write_priors(args.out, priors)
        print(json.dumps({"out": args.out,
                          "latency_s": priors["latency_s"],
                          "corpus": corpus.stats()}, sort_keys=True))
        return 0

    if args.cmd == "replay":
        corpus = corpus_mod.load_corpus(args.corpus)
        print(json.dumps(slo.crossval_report(corpus), sort_keys=True))
        return 0

    op_times = {}
    if args.corpus:
        op_times = _calibrated_op_times(corpus_mod.load_corpus(args.corpus))
    config = SimConfig(
        hosts=args.hosts,
        chips_per_host=args.chips_per_host,
        hosts_per_pipeline=args.hosts_per_pipeline,
        microbatches_per_pipeline=args.microbatches,
        virtual_stages=args.virtual_stages,
        op_times=op_times,
        checkpoint_period_s=args.checkpoint_period_s,
        mode=args.mode,
        priors_path=args.priors)
    scenario = make_scenario(args.scenario, seed=args.seed,
                             hosts=args.hosts, duration_s=args.duration_s)
    run = SimCluster(config, scenario).run()
    print(slo.render(slo.slo_report(run)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
