"""Event-driven cluster model costed by the REAL recovery code paths.

SimCluster owns no recovery model: every incident runs through the same
``degrade.classify.classify_failure`` -> ``degrade.planner.plan_reroute``
(itself ``execution.schedule.replay_schedule`` dependency replay over the
calibrated op durations) -> ``policy.PolicyEngine.decide`` chain the live
system runs, with the simulated clock injected where the live system
injects ``time.monotonic`` and a fresh hermetic ``metrics.Registry`` so
measured history can never leak between runs. What the simulator adds is
only what hardware would have provided: a fleet, a scripted failure
process, and the passage of time.

Time advances through a heapq of (t, seq, ...) events — scenario-scripted
failures/preemptions/traffic swings/capacity arrivals plus the repairs,
spot-lifetime expiries, and recovery completions they cause. Arrivals run
through ``PolicyEngine.decide_grow`` exactly as losses run through
``decide``: the simulator models capacity, never the decision. Goodput is integrated piecewise-constant:
delivered = min(relative_rate, demand); recovery windows deliver zero
(reconfigure blocks the job, as on the real cluster).

Determinism: the only PRNG is ``random.Random(seed)`` (recovery-latency
jitter — scenario events pre-draw their own randomness), the clock is the
event queue, and nothing reads wall time; ``run()`` on equal inputs is
byte-identical.
"""

from __future__ import annotations

import heapq
import random
from dataclasses import dataclass, field

from oobleck_tpu.degrade.classify import classify_failure
from oobleck_tpu.degrade.planner import PipelineSpec, plan_reroute
from oobleck_tpu.execution.schedule import replay_schedule
from oobleck_tpu.obs.fleet import FleetTracker
from oobleck_tpu.policy.engine import PolicyEngine
from oobleck_tpu.policy.signals import priors_provenance
from oobleck_tpu.pool.arbiter import (
    MECH_BORROW_DRAIN,
    MECH_BORROW_SPARE,
    MECH_HOLD,
    MODE_ADAPTIVE,
    PoolArbiter,
)
from oobleck_tpu.pool.leases import ST_EXPIRED, LeaseBook
from oobleck_tpu.pool.tenants import (
    KIND_SERVE,
    KIND_TRAIN,
    TenantRegistry,
    TenantSpec,
)
from oobleck_tpu.sim.scenarios import Scenario
from oobleck_tpu.utils import metrics

# Realized recovery latency jitter band around the scored arm's latency
# (deterministic: drawn from the run's explicit PRNG). Wide enough that
# the measured-EWMA feedback loop sees non-constant samples.
JITTER_LO, JITTER_HI = 0.85, 1.15

# Simulated heartbeat-digest cadence: how often each live host's step
# time reaches the fleet tracker. Scheduled only when the scenario
# scripts "slow" events, so the other scenarios' event streams (and
# their byte-identical renders) are untouched.
TELEMETRY_TICK_S = 5.0

# Shared-pool scenario knobs: explicit constants, never the env, so the
# run stays hermetic. The TTL is the borrow commitment window — expiry
# is the arbiter's reclaim point (mid-peak it re-borrows; in the trough
# the chips ride the grow path home).
POOL_LEASE_TTL_S = 180.0
POOL_TRAIN_TENANT = "train"
POOL_SERVE_TENANT = "serve"


@dataclass
class SimConfig:
    """The candidate configuration under test."""

    hosts: int
    chips_per_host: int = 2
    hosts_per_pipeline: int = 1
    microbatches_per_pipeline: int = 8
    virtual_stages: int = 1
    op_times: dict = field(default_factory=dict)
    checkpoint_period_s: float = 300.0   # <= 0: no durable checkpoint
    max_slowdown: float = 4.0
    degrade_enabled: bool = True
    mode: str = "adaptive"
    priors_path: str | None = None

    @property
    def stages(self) -> int:
        return self.hosts_per_pipeline * self.chips_per_host

    def as_record(self) -> dict:
        return {
            "hosts": self.hosts,
            "chips_per_host": self.chips_per_host,
            "hosts_per_pipeline": self.hosts_per_pipeline,
            "microbatches_per_pipeline": self.microbatches_per_pipeline,
            "virtual_stages": self.virtual_stages,
            "calibrated_ops": len(self.op_times),
            "checkpoint_period_s": self.checkpoint_period_s,
            "max_slowdown": self.max_slowdown,
            "degrade_enabled": self.degrade_enabled,
            "mode": self.mode,
            "priors": priors_provenance(self.priors_path),
        }


@dataclass
class _Pipeline:
    hosts: list[int]
    microbatches: int


class SimCluster:
    """One scenario run over one candidate config. Use ``run()``."""

    def __init__(self, config: SimConfig, scenario: Scenario):
        if scenario.hosts != config.hosts:
            raise ValueError(f"scenario generated for {scenario.hosts} hosts,"
                             f" config has {config.hosts}")
        self.config = config
        self.scenario = scenario
        self.now = 0.0
        self.registry = metrics.Registry()   # hermetic per run
        self.engine = PolicyEngine(
            multihost=True, clock=lambda: self.now, mode=config.mode,
            registry=self.registry, priors_path=config.priors_path)
        self.rng = random.Random(scenario.seed ^ 0x51A0C1)
        self.live: set[int] = set(range(config.hosts))
        self.pipelines: list[_Pipeline] = []
        n_pipes = config.hosts // config.hosts_per_pipeline
        for i in range(n_pipes):
            self.pipelines.append(_Pipeline(
                hosts=list(range(i * config.hosts_per_pipeline,
                                 (i + 1) * config.hosts_per_pipeline)),
                microbatches=config.microbatches_per_pipeline))
        self._total_microbatches = n_pipes * config.microbatches_per_pipeline
        self._makespan_cache: dict[tuple, float] = {}
        # Gray-failure state: per-host step-time factors (> 1 = actively
        # slow), when each slowdown began (detect-latency accounting),
        # and the REAL straggler detector fed by simulated heartbeat
        # digests — explicit thresholds, never the env, so the run stays
        # hermetic. Initialized before _base_rate: _rate() reads it.
        self._slow: dict[int, float] = {}
        self._slow_since: dict[int, float] = {}
        self._slow_cause: dict[int, str] = {}
        self.fleet = FleetTracker(clock=lambda: self.now,
                                  ratio=1.5, z=3.0, persist=3)
        self.detect_to_drain_s: list[float] = []
        self._base_rate = self._rate()
        self._recovery_until = 0.0
        # Control-plane outage window: while now < _master_down_until,
        # losses are buffered (detection stalls, training does not) and
        # decided as ONE reconcile incident when the master returns.
        self._master_down_until = 0.0
        self._outage_buffer: list = []
        # Piecewise-constant goodput integration state.
        self._demand = 1.0
        self._last_t = 0.0
        self._delivered = 0.0
        self._demand_integral = 0.0
        self.incidents: list[dict] = []
        self.lost_work_s = 0.0
        # Shared-pool plane: constructed ONLY when the scenario scripts
        # "serve" events (the TELEMETRY_TICK_S don't-perturb pattern) —
        # every single-tenant scenario keeps its exact event stream and
        # byte-identical render. The REAL arbiter, hermetic: injected
        # clock, injected registry, explicit knobs.
        self.pool: PoolArbiter | None = None
        self._serve_debt = 0.0
        self._leased_at: dict[str, float] = {}
        self._pool_stats = {"granted": 0, "denied": 0, "held": 0,
                            "train_charged_s": 0.0,
                            "chip_seconds_lent": 0.0}
        if any(ev.kind == "serve" for ev in scenario.events):
            tenants = TenantRegistry(clock=lambda: self.now)
            tenants.register(TenantSpec(name=POOL_TRAIN_TENANT,
                                        kind=KIND_TRAIN))
            tenants.register(TenantSpec(name=POOL_SERVE_TENANT,
                                        kind=KIND_SERVE,
                                        slo={"ttft_p99_s": 2.0}))
            self.pool = PoolArbiter(
                tenants=tenants,
                leases=LeaseBook(clock=lambda: self.now),
                registry=self.registry, clock=lambda: self.now,
                mode=MODE_ADAPTIVE, lease_ttl_s=POOL_LEASE_TTL_S,
                min_train_hosts=1, priors_path=config.priors_path)

    # -- throughput model (real replay, cached by schedule shape) ----------- #

    def _makespan(self, microbatches: int) -> float:
        key = (self.config.stages, microbatches, self.config.virtual_stages)
        if key not in self._makespan_cache:
            spec = self._spec(microbatches)
            self._makespan_cache[key] = replay_schedule(
                spec.num_stages, spec.num_microbatches, spec.virtual_stages,
                spec.duration_fn())[0]
        return self._makespan_cache[key]

    def _spec(self, microbatches: int) -> PipelineSpec:
        return PipelineSpec(
            num_stages=self.config.stages,
            num_microbatches=microbatches,
            virtual_stages=self.config.virtual_stages,
            op_times=self.config.op_times)

    def _pipe_factor(self, p: "_Pipeline") -> float:
        """A pipeline runs at the pace of its slowest host (gray failure:
        the straggler's stage gates every microbatch through it)."""
        return max([self._slow.get(h, 1.0) for h in p.hosts] + [1.0])

    def _rate(self) -> float:
        """Microbatches per second at the current layout (replicas run
        concurrently: the step time is the max replica makespan — a
        slowed replica gates the global step, the allreduce barrier)."""
        if not self.pipelines:
            return 0.0
        makespan = max(self._makespan(p.microbatches) * self._pipe_factor(p)
                       for p in self.pipelines)
        if makespan <= 0:
            return 0.0
        return sum(p.microbatches for p in self.pipelines) / makespan

    def _rate_rel(self) -> float:
        if self.now < self._recovery_until or self._base_rate <= 0:
            return 0.0
        return self._rate() / self._base_rate

    def _step_seconds(self) -> float:
        if not self.pipelines:
            return self._makespan(self.config.microbatches_per_pipeline)
        return max(self._makespan(p.microbatches) * self._pipe_factor(p)
                   for p in self.pipelines)

    # -- bookkeeping --------------------------------------------------------- #

    def _advance(self, t: float) -> None:
        dt = t - self._last_t
        if dt > 0:
            self._delivered += min(self._rate_rel(), self._demand) * dt
            self._demand_integral += self._demand * dt
            self._last_t = t
        self.now = t

    def _ip(self, host: int) -> str:
        return f"10.{(host >> 16) & 255}.{(host >> 8) & 255}.{host & 255}"

    def _staleness(self) -> tuple[float | None, float]:
        """(staleness_steps, staleness_seconds) against the periodic
        durable checkpoint; (None, 0) when checkpoints are off."""
        period = self.config.checkpoint_period_s
        if period <= 0:
            return None, 0.0
        stale_s = self.now % period
        step_s = self._step_seconds()
        return (stale_s / step_s if step_s > 0 else 0.0), stale_s

    def _spares(self) -> list[int]:
        assigned = {h for p in self.pipelines for h in p.hosts}
        return sorted(h for h in self.live - assigned
                      if not self.engine.is_quarantined(self._ip(h)))

    def _rebuild(self) -> None:
        """Re-instantiate a balanced layout over every usable live host,
        spreading the global microbatch budget evenly (remainder to the
        lowest-indexed pipelines, deterministically)."""
        usable = sorted({h for p in self.pipelines for h in p.hosts}
                        | set(self._spares()))
        hpp = self.config.hosts_per_pipeline
        n = len(usable) // hpp
        self.pipelines = []
        if n == 0:
            return
        base, rem = divmod(self._total_microbatches, n)
        for i in range(n):
            self.pipelines.append(_Pipeline(
                hosts=usable[i * hpp:(i + 1) * hpp],
                microbatches=base + (1 if i < rem else 0)))

    # -- the incident -------------------------------------------------------- #

    def _handle_join(self, events: list) -> None:
        """One grow incident: a batch of same-instant arrivals decided by
        the REAL ``PolicyEngine.decide_grow`` (the same chain the live
        master runs), then applied to the cluster model — absorb_spare
        parks the arrivals (zero stall), grow_dp keeps every surviving
        pipeline's host group intact and adds replica(s) over the
        arrivals (the batch redistribution is the stall), grow_reshape
        re-instantiates the whole layout over every usable host."""
        events = [e for e in events if e.host not in self.live]
        if not events:
            return
        joined = sorted(e.host for e in events)
        joined_ips = [self._ip(h) for h in joined]
        hints = {self._ip(e.host): e.repair_delay_s
                 for e in events if e.repair_delay_s > 0}
        hpp = self.config.hosts_per_pipeline
        current = len({h for p in self.pipelines for h in p.hosts})
        staleness_steps, _ = self._staleness()
        dp_ok = bool(self.pipelines) and len(joined) >= hpp
        decision = self.engine.decide_grow(
            joined_ips,
            current_hosts=max(current, 1),
            dp_feasible=dp_ok,
            dp_reason="" if dp_ok
            else f"arrivals({len(joined)})<pipeline_unit({hpp})",
            staleness_steps=staleness_steps,
            step_seconds=self._step_seconds(),
            lifetime_hints=hints,
            cause=events[0].cause or "join")

        rate_before = self._rate()
        self.live |= set(joined)
        stalls = decision.mechanism != "absorb_spare"
        if decision.mechanism == "grow_dp":
            # Survivor groups untouched; arrivals form whole new replica
            # blocks; the FIXED global microbatch budget re-spreads evenly
            # (remainder to the lowest-indexed pipelines, as _rebuild).
            for i in range(len(joined) // hpp):
                self.pipelines.append(_Pipeline(
                    hosts=joined[i * hpp:(i + 1) * hpp], microbatches=0))
            base, rem = divmod(self._total_microbatches, len(self.pipelines))
            for i, p in enumerate(self.pipelines):
                p.microbatches = base + (1 if i < rem else 0)
        elif decision.mechanism == "grow_reshape":
            self._rebuild()

        realized = (decision.arms[decision.mechanism]["latency_s"]
                    * self.rng.uniform(JITTER_LO, JITTER_HI))
        self.engine.observe_measured(decision.mechanism, realized)
        if stalls:
            self._recovery_until = max(self._recovery_until,
                                       self.now + realized)
            self._push(self._recovery_until, "recovered", None)

        reg = self.registry
        reg.histogram(
            "oobleck_sim_recovery_seconds",
            "Simulated realized recovery latency by mechanism",
        ).observe(realized, mechanism=decision.mechanism)
        reg.counter(
            "oobleck_sim_incidents_total",
            "Simulated incidents by mechanism and cause",
        ).inc(mechanism=decision.mechanism, cause=events[0].cause or "join")
        self.incidents.append({
            "t": round(self.now, 6),
            "direction": "grow",
            "joined_hosts": len(joined),
            "lost_hosts": 0,
            "cause": events[0].cause or "join",
            "correlated": len(joined) > 1,
            "proactive": False,
            "mechanism": decision.mechanism,
            "reason": decision.reason,
            "projected_cost_s": round(decision.projected_cost_s, 6),
            "realized_recovery_s": round(realized, 6),
            "arms": decision.arms,
            "rate_before": round(rate_before, 6),
            "rate_after": round(self._rate(), 6),
            "live_hosts": len(self.live),
            "pipelines": len(self.pipelines),
        })

    def _handle_incident(self, events: list) -> None:
        events = [e for e in events if e.host in self.live]
        if not events:
            return
        lost = [e.host for e in events]
        proactive = all(e.kind == "preempt" for e in events)
        cause = events[0].cause
        lost_ips = [self._ip(h) for h in lost]
        for e in events:
            self.engine.observe_failure(self._ip(e.host), cause=e.cause)
        self.live -= set(lost)

        dead_idx = [i for i, p in enumerate(self.pipelines)
                    if any(h in p.hosts for h in lost)]
        if not dead_idx:
            return  # spare-only loss: no layout change, no recovery stall

        # Real classifier + planner (single-host incidents only: the
        # policy plane prices correlated losses reroute-infeasible before
        # any plan could matter, exactly like the live master).
        retention = None
        feasible, reason, plan = True, "", None
        if len(lost) == 1 and self.config.degrade_enabled:
            ranks = [[h * self.config.chips_per_host + c
                      for h in p.hosts
                      for c in range(self.config.chips_per_host)]
                     for p in self.pipelines]
            report = classify_failure(lost[0], ranks,
                                      self.config.chips_per_host)
            specs = [self._spec(p.microbatches) for p in self.pipelines]
            plan = plan_reroute(report, specs,
                                max_slowdown=self.config.max_slowdown)
            feasible, reason = plan.feasible, plan.reason
            if plan.feasible:
                retention = plan.throughput_retention

        staleness_steps, stale_s = self._staleness()
        survivor_frac = (len(self.live) / (len(self.live) + len(lost))
                         if self.live else 0.0)
        decision = self.engine.decide(
            lost_ips,
            degrade_enabled=self.config.degrade_enabled,
            reroute_retention=retention,
            reroute_feasible=feasible,
            reroute_reason=reason,
            survivor_frac=survivor_frac,
            staleness_steps=staleness_steps,
            step_seconds=self._step_seconds(),
            proactive=proactive,
            cause=cause)

        rate_before = self._rate()
        if decision.mechanism == "reroute" and plan is not None \
                and plan.feasible:
            survivors = [self.pipelines[i] for i in plan.report.surviving]
            for i, p in zip(plan.report.surviving, survivors):
                p.microbatches = plan.new_microbatches[i]
            self.pipelines = survivors
        else:
            # Dropping a dead pipeline releases its surviving hosts into
            # the spare pool (they are live but unassigned), which the
            # rebuild folds straight back in.
            for i in reversed(dead_idx):
                self.pipelines.pop(i)
            self._rebuild()
            if decision.mechanism == "restore":
                self.lost_work_s += stale_s

        realized = (decision.arms[decision.mechanism]["latency_s"]
                    * self.rng.uniform(JITTER_LO, JITTER_HI))
        self.engine.observe_measured(decision.mechanism, realized)
        self._recovery_until = max(self._recovery_until, self.now + realized)
        self._push(self._recovery_until, "recovered", None)

        reg = self.registry
        reg.histogram(
            "oobleck_sim_recovery_seconds",
            "Simulated realized recovery latency by mechanism",
        ).observe(realized, mechanism=decision.mechanism)
        reg.counter(
            "oobleck_sim_incidents_total",
            "Simulated incidents by mechanism and cause",
        ).inc(mechanism=decision.mechanism, cause=cause)
        self.incidents.append({
            "t": round(self.now, 6),
            "lost_hosts": len(lost),
            "cause": cause,
            "correlated": len(lost) > 1,
            "proactive": proactive,
            "mechanism": decision.mechanism,
            "reason": decision.reason,
            "projected_cost_s": round(decision.projected_cost_s, 6),
            "realized_recovery_s": round(realized, 6),
            "arms": decision.arms,
            "rate_before": round(rate_before, 6),
            "rate_after": round(self._rate(), 6),
            "live_hosts": len(self.live),
            "pipelines": len(self.pipelines),
        })

    # -- control-plane outage (master_outage scenario) ----------------------- #

    def _buffer_outage(self, events: list) -> None:
        """A failure landing while the master is down: the broken
        replica stops delivering immediately (that is physics, not
        policy), but detection and the recovery decision wait for the
        restarted master's reconcile — nobody is watching."""
        events = [e for e in events if e.host in self.live]
        if not events:
            return
        self.live -= {e.host for e in events}
        dead_idx = [i for i, p in enumerate(self.pipelines)
                    if any(e.host in p.hosts for e in events)]
        for i in reversed(dead_idx):
            self.pipelines.pop(i)
        self._outage_buffer.extend(events)

    def _reconcile_outage(self) -> None:
        """The restarted master's journal-vs-reality reconcile: every
        host that died during the outage and is still gone is folded
        into ONE batched incident through the REAL policy chain, with
        cause=master_outage — mirroring the live master's
        _reconcile_after_window (one decision for all no-shows; reroute
        is never an arm, the moment for an in-place fix passed with the
        outage). Hosts repaired inside the window are the sim analogue
        of agents that reattached: not an incident at all."""
        events = [e for e in self._outage_buffer if e.host not in self.live]
        self._outage_buffer = []
        if not events:
            return
        lost_ips = [self._ip(e.host) for e in events]
        for ip in lost_ips:
            self.engine.observe_failure(ip, cause="master_outage")
        staleness_steps, stale_s = self._staleness()
        survivor_frac = (len(self.live) / (len(self.live) + len(events))
                         if self.live else 0.0)
        decision = self.engine.decide(
            lost_ips,
            degrade_enabled=self.config.degrade_enabled,
            reroute_retention=None,
            reroute_feasible=False,
            reroute_reason="stale_membership_after_master_outage",
            survivor_frac=survivor_frac,
            staleness_steps=staleness_steps,
            step_seconds=self._step_seconds(),
            proactive=False,
            cause="master_outage")
        rate_before = self._rate()
        self._rebuild()
        if decision.mechanism == "restore":
            self.lost_work_s += stale_s
        realized = (decision.arms[decision.mechanism]["latency_s"]
                    * self.rng.uniform(JITTER_LO, JITTER_HI))
        self.engine.observe_measured(decision.mechanism, realized)
        self._recovery_until = max(self._recovery_until, self.now + realized)
        self._push(self._recovery_until, "recovered", None)

        reg = self.registry
        reg.histogram(
            "oobleck_sim_recovery_seconds",
            "Simulated realized recovery latency by mechanism",
        ).observe(realized, mechanism=decision.mechanism)
        reg.counter(
            "oobleck_sim_incidents_total",
            "Simulated incidents by mechanism and cause",
        ).inc(mechanism=decision.mechanism, cause="master_outage")
        self.incidents.append({
            "t": round(self.now, 6),
            "lost_hosts": len(events),
            "cause": "master_outage",
            "correlated": len(events) > 1,
            "proactive": False,
            "mechanism": decision.mechanism,
            "reason": decision.reason,
            "projected_cost_s": round(decision.projected_cost_s, 6),
            "realized_recovery_s": round(realized, 6),
            "arms": decision.arms,
            "rate_before": round(rate_before, 6),
            "rate_after": round(self._rate(), 6),
            "live_hosts": len(self.live),
            "pipelines": len(self.pipelines),
        })

    # -- gray failures (straggler scenario) ---------------------------------- #

    def _host_of(self, ip: str) -> int:
        a, b, c = (int(x) for x in ip.split(".")[1:])
        return (a << 16) | (b << 8) | c

    def _set_slow(self, ev) -> None:
        """Apply one scripted "slow" event: the host's step-time factor
        changes (1.0 = recovered). The rate breakpoint lands via the
        _advance() already done for this event's timestamp."""
        if ev.host not in self.live:
            return
        if ev.factor > 1.0:
            self._slow[ev.host] = ev.factor
            self._slow_since.setdefault(ev.host, self.now)
            self._slow_cause[ev.host] = ev.cause or "slowdown"
        else:
            self._slow.pop(ev.host, None)
            self._slow_since.pop(ev.host, None)

    def _telemetry_tick(self) -> None:
        """One simulated heartbeat round: every assigned live host
        reports its OWN step time (pipeline makespan x its factor) to
        the REAL FleetTracker; a consumed flag runs the REAL
        decide_slowdown chain. The detector, thresholds, persistence
        gate and one-incident dedup are the production code — the sim
        only supplies the digests."""
        for p in self.pipelines:
            span = self._makespan(p.microbatches)
            for h in p.hosts:
                if h in self.live:
                    self.fleet.ingest(self._ip(h), {
                        "v": 1, "step": 0,
                        "step_s": span * self._slow.get(h, 1.0)})
        slow_ip = self.fleet.consume_straggler()
        if slow_ip is not None:
            self._handle_slowdown(slow_ip)

    def _handle_slowdown(self, ip: str) -> None:
        host = self._host_of(ip)
        ratio = self.fleet.ratio(ip) or 1.0
        cause = self._slow_cause.get(host, "slowdown")
        n = len(self.live)
        decision = self.engine.decide_slowdown(
            ip, slowdown_ratio=ratio,
            survivor_frac=(n - 1) / n if n else 1.0,
            cause=cause)
        detect_s = (round(self.now - self._slow_since[host], 6)
                    if host in self._slow_since else None)
        rate_before = self._rate()
        realized = 0.0
        active = decision.mechanism in ("drain", "quarantine")
        if active:
            # Proactive drain: the sick host checkpoints and leaves; the
            # survivors re-instantiate without it. No host died — the
            # drain cost is the only stall.
            self.live.discard(host)
            self._slow.pop(host, None)
            self.fleet.clear(ip)
            dead_idx = [i for i, p in enumerate(self.pipelines)
                        if host in p.hosts]
            for i in reversed(dead_idx):
                self.pipelines.pop(i)
            self._rebuild()
            realized = (decision.arms[decision.mechanism]["latency_s"]
                        * self.rng.uniform(JITTER_LO, JITTER_HI))
            self.engine.observe_measured(decision.mechanism, realized)
            self._recovery_until = max(self._recovery_until,
                                       self.now + realized)
            self._push(self._recovery_until, "recovered", None)
            if detect_s is not None:
                self.detect_to_drain_s.append(detect_s)
        reg = self.registry
        if active:
            reg.histogram(
                "oobleck_sim_recovery_seconds",
                "Simulated realized recovery latency by mechanism",
            ).observe(realized, mechanism=decision.mechanism)
        reg.counter(
            "oobleck_sim_incidents_total",
            "Simulated incidents by mechanism and cause",
        ).inc(mechanism=decision.mechanism, cause=cause)
        self.incidents.append({
            "t": round(self.now, 6),
            "lost_hosts": 1 if active else 0,
            "cause": cause,
            "correlated": False,
            "proactive": active,
            "slowdown_ratio": round(ratio, 6),
            "detect_s": detect_s,
            "mechanism": decision.mechanism,
            "reason": decision.reason,
            "projected_cost_s": round(decision.projected_cost_s, 6),
            "realized_recovery_s": round(realized, 6),
            "arms": decision.arms,
            "rate_before": round(rate_before, 6),
            "rate_after": round(self._rate(), 6),
            "live_hosts": len(self.live),
            "pipelines": len(self.pipelines),
        })

    # -- shared chip pool (shared_pool scenario) ------------------------------ #

    def _pool_train_hosts(self) -> int:
        return len({h for p in self.pipelines for h in p.hosts})

    def _handle_serve(self, ev) -> None:
        """One scripted serve-pressure step: ``demand`` is the co-tenant
        serve group's priced SLO debt. A peak step with no active lease
        is a borrow incident through the REAL arbiter; a trough step is
        just the debt clearing — reclaim happens at lease expiry (the
        sweep), absence of renewal being the off-peak signal, exactly
        like the live master."""
        self._serve_debt = max(ev.demand, 0.0)
        if self._serve_debt > 0 and not self.pool.leases.active():
            self._pool_borrow(ev)

    def _pool_borrow(self, ev) -> None:
        spares = self._spares()
        decision = self.pool.decide_borrow(
            POOL_SERVE_TENANT, 1,
            train_hosts=self._pool_train_hosts(),
            spare_hosts=len(spares),
            slo_debt_s=self._serve_debt,
            lender=POOL_TRAIN_TENANT,
            cause=ev.cause or "serve_peak")
        rate_before = self._rate()
        realized = 0.0
        host: int | None = None
        if decision.mechanism == MECH_BORROW_SPARE and spares:
            host = spares[-1]
        elif decision.mechanism == MECH_BORROW_DRAIN:
            assigned = sorted(h for p in self.pipelines for h in p.hosts)
            host = assigned[-1] if assigned else None
        if host is None:
            self._pool_stats["denied"] += 1
        else:
            lease = self.pool.leases.grant(
                POOL_SERVE_TENANT, [self._ip(host)], POOL_LEASE_TTL_S,
                lender=POOL_TRAIN_TENANT, trace_id=decision.trace_id or "")
            self._leased_at[lease.lease_id] = self.now
            self._pool_stats["granted"] += 1
            self.live.discard(host)
            realized = (decision.arms[decision.mechanism]["latency_s"]
                        * self.rng.uniform(JITTER_LO, JITTER_HI))
            self.pool.observe_measured(decision.mechanism, realized)
            if decision.mechanism == MECH_BORROW_DRAIN:
                # Proactive drain, the slowdown path's shape: checkpoint
                # flush + clean exit, survivors re-instantiate without
                # the victim. No host died — the drain is the only stall.
                dead = [i for i, p in enumerate(self.pipelines)
                        if host in p.hosts]
                for i in reversed(dead):
                    self.pipelines.pop(i)
                self._rebuild()
                self._recovery_until = max(self._recovery_until,
                                           self.now + realized)
                self._push(self._recovery_until, "recovered", None)
            self._pool_stats["train_charged_s"] += realized
            self.pool.tenants.attribute(
                decision.trace_id or "", {POOL_TRAIN_TENANT: realized},
                cause="pool_borrow")
            self._push(round(self.now + POOL_LEASE_TTL_S, 6),
                       "lease_expire", lease.lease_id)
        self._pool_incident(decision, ev.cause or "serve_peak",
                            realized, rate_before)

    def _pool_expire(self, lease_id: str) -> None:
        """Lease-sweep point: the REAL arbiter scores hold-vs-reclaim.
        A hold (borrower renewed under live pressure) extends and
        re-arms the sweep; otherwise the chips ride the grow path home
        and training re-instantiates over them."""
        lease = self.pool.leases.get(lease_id)
        if lease is None:
            return
        if not lease.expired(self.now):
            self._push(round(lease.expires_at, 6), "lease_expire", lease_id)
            return
        decision = self.pool.decide_reclaim(
            lease, train_hosts=self._pool_train_hosts(),
            slo_debt_s=self._serve_debt, cause="expiry")
        rate_before = self._rate()
        realized = 0.0
        if decision.mechanism == MECH_HOLD:
            self.pool.leases.extend(lease_id, POOL_LEASE_TTL_S)
            self._push(round(self.now + POOL_LEASE_TTL_S, 6),
                       "lease_expire", lease_id)
            self._pool_stats["held"] += 1
        else:
            ended = self.pool.leases.end(lease_id, ST_EXPIRED)
            for ip in ended.hosts:
                self.live.add(self._host_of(ip))
            self._rebuild()
            realized = (decision.arms[decision.mechanism]["latency_s"]
                        * self.rng.uniform(JITTER_LO, JITTER_HI))
            self.pool.observe_measured(decision.mechanism, realized)
            self._recovery_until = max(self._recovery_until,
                                       self.now + realized)
            self._push(self._recovery_until, "recovered", None)
            self._pool_stats["train_charged_s"] += realized
            self._pool_stats["chip_seconds_lent"] += len(ended.hosts) * (
                self.now - self._leased_at.pop(lease_id, self.now))
            self.pool.tenants.attribute(
                decision.trace_id or "", {POOL_TRAIN_TENANT: realized},
                cause="pool_expiry")
        self._pool_incident(decision, "expiry", realized, rate_before)

    def _pool_incident(self, decision, cause: str, realized: float,
                       rate_before: float) -> None:
        reg = self.registry
        if realized > 0:
            reg.histogram(
                "oobleck_sim_recovery_seconds",
                "Simulated realized recovery latency by mechanism",
            ).observe(realized, mechanism=decision.mechanism)
        reg.counter(
            "oobleck_sim_incidents_total",
            "Simulated incidents by mechanism and cause",
        ).inc(mechanism=decision.mechanism, cause=cause)
        self.incidents.append({
            "t": round(self.now, 6),
            "direction": f"pool_{decision.direction}",
            "lost_hosts": 0,
            "cause": cause,
            "correlated": False,
            "proactive": True,
            "tenant": decision.tenant,
            "slo_debt_s": round(decision.slo_debt_s, 6),
            "mechanism": decision.mechanism,
            "reason": decision.reason,
            "projected_cost_s": round(decision.projected_cost_s or 0.0, 6),
            "realized_recovery_s": round(realized, 6),
            "arms": decision.arms,
            "rate_before": round(rate_before, 6),
            "rate_after": round(self._rate(), 6),
            "live_hosts": len(self.live),
            "pipelines": len(self.pipelines),
        })

    # -- the run ------------------------------------------------------------- #

    def _push(self, t: float, kind: str, payload) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, kind, payload))

    def run(self) -> dict:
        """Drive the scenario to its end; returns the raw run record the
        SLO reducer consumes (plain JSON types, deterministic)."""
        self._heap: list = []
        self._seq = 0
        for ev in self.scenario.events:
            self._push(ev.t, "scenario", ev)
        duration = self.scenario.duration_s
        if any(ev.kind == "slow" for ev in self.scenario.events):
            # Heartbeat-digest cadence for the fleet-health plane; only
            # scheduled when gray failures are scripted, so every other
            # scenario's event stream stays byte-identical.
            t = TELEMETRY_TICK_S
            while t < duration:
                self._push(round(t, 6), "telemetry", None)
                t += TELEMETRY_TICK_S
        while self._heap:
            t, _, kind, payload = heapq.heappop(self._heap)
            if t > duration:
                break
            self._advance(t)
            if kind == "scenario":
                if payload.kind == "traffic":
                    self._demand = payload.demand
                elif payload.kind in ("fail", "preempt"):
                    batch = [payload]
                    while (self._heap and self._heap[0][0] == t
                           and self._heap[0][2] == "scenario"
                           and getattr(self._heap[0][3], "kind", "")
                           in ("fail", "preempt")
                           and self._heap[0][3].incident_id
                           == payload.incident_id):
                        batch.append(heapq.heappop(self._heap)[3])
                    for ev in batch:
                        if ev.host in self.live:
                            self._push(t + max(ev.repair_delay_s, 0.0),
                                       "repair", ev.host)
                    if t < self._master_down_until:
                        self._buffer_outage(batch)
                    else:
                        self._handle_incident(batch)
                elif payload.kind == "join":
                    # Same-instant arrivals sharing an incident_id are ONE
                    # grow incident — the live master's JOIN-window batch.
                    batch = [payload]
                    while (self._heap and self._heap[0][0] == t
                           and self._heap[0][2] == "scenario"
                           and getattr(self._heap[0][3], "kind", "")
                           == "join"
                           and self._heap[0][3].incident_id
                           == payload.incident_id):
                        batch.append(heapq.heappop(self._heap)[3])
                    if t < self._master_down_until:
                        # No master to JOIN: the arrival parks and
                        # re-dials once the master is back (lifetime
                        # clocks from admission, matching the live
                        # master reading the hint at admit time).
                        for ev in batch:
                            self._push(self._master_down_until,
                                       "scenario", ev)
                        continue
                    for ev in batch:
                        if ev.repair_delay_s > 0:
                            # Spot lifetime: the host dies for good when
                            # the advertised deadline lapses.
                            self._push(t + ev.repair_delay_s, "expire",
                                       ev.host)
                    self._handle_join(batch)
                elif payload.kind == "slow":
                    self._set_slow(payload)
                elif payload.kind == "serve":
                    if self.pool is not None:
                        self._handle_serve(payload)
                elif payload.kind == "master_down":
                    # The control plane dies; training does not. Extend
                    # (never shorten) on overlapping outages.
                    up_at = t + max(payload.repair_delay_s, 0.0)
                    if up_at > self._master_down_until:
                        self._master_down_until = up_at
                        self._push(up_at, "master_up", None)
            elif kind == "telemetry":
                if t >= self._master_down_until:
                    self._telemetry_tick()
            elif kind == "master_up":
                if t >= self._master_down_until:
                    self._reconcile_outage()
            elif kind == "lease_expire":
                if self.pool is not None:
                    self._pool_expire(payload)
            elif kind == "expire":
                if payload in self.live:
                    from oobleck_tpu.sim.scenarios import ScenarioEvent

                    ev = ScenarioEvent(t=t, kind="fail", host=payload,
                                       cause="spot_lifetime")
                    if t < self._master_down_until:
                        self._buffer_outage([ev])
                    else:
                        self._handle_incident([ev])
            elif kind == "repair":
                if payload not in self.live:
                    self.live.add(payload)
                    # A total outage ends on the first usable capacity;
                    # otherwise repaired hosts wait as spares for the next
                    # incident's re-instantiation to fold them in.
                    if not self.pipelines and len(self._spares()) \
                            >= self.config.hosts_per_pipeline:
                        self._rebuild()
            # "recovered" events change no state: _rate_rel() reads
            # _recovery_until against the clock; the event exists so the
            # piecewise integration has a breakpoint at the edge.
        self._advance(duration)
        goodput = (self._delivered / self._demand_integral
                   if self._demand_integral > 0 else 0.0)
        self.registry.gauge(
            "oobleck_sim_goodput_ratio",
            "Delivered/demanded goodput over the scenario",
        ).set(goodput)
        out = {
            "scenario": {
                "name": self.scenario.name,
                "seed": self.scenario.seed,
                "hosts": self.scenario.hosts,
                "duration_s": self.scenario.duration_s,
                "events": len(self.scenario.events),
            },
            "config": self.config.as_record(),
            "incidents": self.incidents,
            "goodput_ratio": round(goodput, 6),
            "lost_work_s": round(self.lost_work_s, 6),
            "detect_to_drain_s": list(self.detect_to_drain_s),
            "final": {
                "live_hosts": len(self.live),
                "pipelines": len(self.pipelines),
                "quarantined": len(self.engine.health.quarantined()),
            },
        }
        if self.pool is not None:
            # Present only for shared-pool scenarios, so every other
            # scenario's run record (and render) stays byte-identical.
            snap = self.pool.leases.snapshot()
            out["pool"] = {
                "granted": self._pool_stats["granted"],
                "denied": self._pool_stats["denied"],
                "held": self._pool_stats["held"],
                "ended": snap["ended"],
                "still_active": len(snap["active"]),
                "chip_seconds_lent": round(
                    self._pool_stats["chip_seconds_lent"], 6),
                "train_charged_s": round(
                    self._pool_stats["train_charged_s"], 6),
            }
        return out
