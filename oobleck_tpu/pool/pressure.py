"""Serve-side pressure monitor: traffic peaks become borrow requests.

Reads the serve metrics the batcher already publishes — queue depth,
TTFT p99, and the ``deadline_queued`` outcome rate (requests whose
deadline expired while still QUEUED: the unambiguous "not enough chips"
signal, since a request that never reached a slot cannot blame model
speed) — and turns them into a pressure verdict with hysteresis, plus
an SLO-debt price in seconds the arbiter can weigh against training's
preemption cost in one currency.

The debt model: each ``sample()`` computes a dimensionless pressure
score — how far queue depth, TTFT p99, and the deadline_queued rate sit
above their thresholds — and ``slo_debt_s(horizon)`` projects it over
the lease horizon, clamped so one pathological sample cannot price the
whole fleet away. Hysteresis (``OOBLECK_POOL_HYST`` consecutive samples)
keeps one burst from triggering a borrow and one quiet poll from
triggering a reclaim: chip movement costs real drain/grow work, so the
monitor must be slower than the noise.

Runs in the SERVE process (where the metrics live); the computed
pressure dict rides the POOL_BORROW request to the master, which never
needs serve-side scrape access.
"""

from __future__ import annotations

import os
import time

from oobleck_tpu.utils import metrics

ENV_QUEUE_HIGH = "OOBLECK_POOL_QUEUE_HIGH"
ENV_TTFT_SLO = "OOBLECK_POOL_TTFT_SLO_S"
ENV_HYST = "OOBLECK_POOL_HYST"

DEFAULT_QUEUE_HIGH = 8.0     # queued requests before pressure counts
DEFAULT_TTFT_SLO_S = 2.0     # TTFT p99 target
DEFAULT_HYST = 2             # consecutive samples to flip the verdict

# One sample's score is clamped here before projection: debt prices a
# peak, it must not price an outage (that is the failure planes' job).
MAX_SCORE = 2.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class PressureMonitor:
    """Hysteresis-filtered serve pressure for one replica group."""

    def __init__(self, *, registry=None, clock=time.monotonic,
                 queue_high: float | None = None,
                 ttft_slo_s: float | None = None,
                 hysteresis: int | None = None):
        self._registry = registry
        self._clock = clock
        self.queue_high = (queue_high if queue_high is not None
                           else _env_float(ENV_QUEUE_HIGH,
                                           DEFAULT_QUEUE_HIGH))
        self.ttft_slo_s = (ttft_slo_s if ttft_slo_s is not None
                           else _env_float(ENV_TTFT_SLO, DEFAULT_TTFT_SLO_S))
        self.hysteresis = max(int(hysteresis if hysteresis is not None
                                  else _env_float(ENV_HYST, DEFAULT_HYST)), 1)
        self._pressured = False
        self._high_streak = 0
        self._low_streak = 0
        self._last_t: float | None = None
        self._last_deadline_queued = 0.0
        self._last: dict = {}

    # -- raw reads ----------------------------------------------------------- #

    def _reg(self):
        return self._registry or metrics.registry()

    def _queue_depth(self) -> float:
        series = self._reg().gauge("oobleck_serve_queue_depth", "").series()
        return max((s["value"] for s in series), default=0.0)

    def _ttft_p99(self) -> float | None:
        hist = self._reg().histogram("oobleck_serve_ttft_seconds", "")
        merged = metrics.merge_histogram_series(hist.series())
        if merged is None:
            return None
        return metrics.histogram_percentile(merged, 0.99)

    def _deadline_queued_total(self) -> float:
        counter = self._reg().counter("oobleck_serve_requests_total", "")
        return sum(s["value"] for s in counter.series()
                   if s["labels"].get("outcome") == "deadline_queued")

    # -- the sample ---------------------------------------------------------- #

    def sample(self) -> dict:
        """One pressure reading; call at the load generator's poll cadence.

        score = how far above threshold each signal sits, summed:
        queue_depth/high - 1, ttft_p99/slo - 1, and the deadline_queued
        rate (each clamped at >= 0; the rate term saturates at 1)."""
        now = self._clock()
        queue = self._queue_depth()
        ttft = self._ttft_p99()
        dq_total = self._deadline_queued_total()
        if self._last_t is not None and now > self._last_t:
            dq_rate = max(dq_total - self._last_deadline_queued, 0.0) \
                / (now - self._last_t)
        else:
            dq_rate = 0.0
        self._last_t = now
        self._last_deadline_queued = dq_total

        score = max(queue / self.queue_high - 1.0, 0.0) if self.queue_high \
            else 0.0
        if ttft is not None and self.ttft_slo_s > 0:
            score += max(ttft / self.ttft_slo_s - 1.0, 0.0)
        score += min(dq_rate, 1.0)
        score = min(score, MAX_SCORE)

        if score > 0:
            self._high_streak += 1
            self._low_streak = 0
        else:
            self._low_streak += 1
            self._high_streak = 0
        if not self._pressured and self._high_streak >= self.hysteresis:
            self._pressured = True
        elif self._pressured and self._low_streak >= self.hysteresis:
            self._pressured = False

        self._last = {
            "queue_depth": round(queue, 6),
            "ttft_p99_s": round(ttft, 6) if ttft is not None else None,
            "deadline_queued_rate": round(dq_rate, 6),
            "score": round(score, 6),
            "pressured": self._pressured,
        }
        reg = self._reg()
        reg.gauge(
            "oobleck_pool_pressure_score",
            "Serve pressure score feeding pool borrow requests",
        ).set(score)
        return dict(self._last)

    @property
    def pressured(self) -> bool:
        return self._pressured

    def slo_debt_s(self, horizon_s: float) -> float:
        """The last sample's score projected over ``horizon_s`` — the
        seconds of SLO-debt the arbiter charges to every arm that leaves
        the pressure unrelieved. Zero before the first sample and zero
        the moment the score clears (debt is a live price, not a
        grudge)."""
        score = float(self._last.get("score") or 0.0)
        return min(score, MAX_SCORE) * max(float(horizon_s), 0.0)

    def as_payload(self, *, horizon_s: float) -> dict:
        """The pressure dict that rides a POOL_BORROW request: the last
        sample plus the debt already priced in seconds, so the master
        never needs serve-side scrape access."""
        return dict(self._last,
                    slo_debt_s=round(self.slo_debt_s(horizon_s), 6))
