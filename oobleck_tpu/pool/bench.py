"""Shared chip-pool bench: one full borrow/return cycle under a traffic
wave.

Composes the two proven bench harnesses: the scripted control-plane
fleet from ``elastic/master_bench.py`` (real TCP, no workers) as the
training tenant, and the real serving plane from ``serve/bench.py``
(tiny model, CPU-friendly) as the serve tenant. A ``traffic_wave``
chaos directive sets the peak request rate; the serve-side
``PressureMonitor`` reads the plane's own metrics and prices the peak
as SLO debt, which rides a POOL_BORROW to the arbiter.

Measured, in order:

  * borrow_latency_s — POOL_BORROW request -> lease granted (the
    arbiter's classify -> score -> grant path over real sockets);
  * grant_broadcast_s — request -> LEASE_GRANT landed at EVERY agent
    (the drain order reaching the fleet);
  * serve attainment at the peak — completed / issued requests; the
    acceptance bar is 1.0 (zero failed or dropped while chips move);
  * training yield — victim drains via the proactive path: zero
    recovery broadcasts, zero respawns, and the goodput retention of
    the shrunken fleet;
  * reclaim — off-peak release rides LEASE_RECLAIM through the grow
    path; release_to_reclaim_s is request -> verb at every survivor.

Prints ONE JSON line (consumed by bench.py's "pool" key and
`make pool-bench`).
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import tempfile
import threading
import time

import numpy as np

from oobleck_tpu.config import OobleckArguments
from oobleck_tpu.elastic import journal as journal_mod
from oobleck_tpu.elastic.master_bench import (
    ScriptedAgent,
    _hard_kill,
    _start_master,
)
from oobleck_tpu.elastic.message import (
    JOINED_KEY,
    LEASE_KEY,
    TENANT_KEY,
    RequestType,
    ResponseType,
    recv_msg,
    send_request,
)
from oobleck_tpu.policy.engine import DECISION_KEY
from oobleck_tpu.pool import arbiter as pool_arbiter
from oobleck_tpu.pool.pressure import PressureMonitor
from oobleck_tpu.utils import chaos as chaos_mod
from oobleck_tpu.utils import metrics

# Peak rate comes from the chaos directive (override via OOBLECK_CHAOS).
DEFAULT_WAVE = "traffic_wave=40:2"
AGENTS = ("10.8.0.1", "10.8.0.2", "10.8.0.3", "10.8.0.4")
LEASE_TTL_S = 60.0
# Sized so the peak outruns the tiny plane's throughput: two decode
# lanes against a 24-request burst holds a real queue — long enough for
# the pressure monitor to see it, short enough for a CPU bench.
PEAK_REQUESTS = 24
GEN_TOKENS = 48
SERVE_LANES = 2
PHASE_TIMEOUT_S = 30.0
# Debt floor before borrowing: the arbiter would grant on less, but the
# bench should measure a decisive peak, not a threshold-grazing one.
MIN_DEBT_S = 30.0


def _fire_wave(port: int, *, n_requests: int, rate_hz: float,
               gen_tokens: int, seed: int = 0) -> dict:
    """Open-loop Poisson burst at the chaos-directed rate. Returns after
    the last ARRIVAL (threads still in flight) so the caller can sample
    pressure mid-wave. A request that raises or returns non-200 is a
    dropped request — the bench's failure bar."""
    import http.client

    rng = np.random.default_rng(seed)
    ok: list[int] = []
    failed: list[str] = []
    lock = threading.Lock()

    def one_request(tokens: list[int]) -> None:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
            body = json.dumps({"tokens": tokens, "max_tokens": gen_tokens})
            conn.request("POST", "/v1/generate", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = json.loads(resp.read())
            conn.close()
            if resp.status != 200:
                raise RuntimeError(f"status {resp.status}: {out}")
            with lock:
                ok.append(len(out["tokens"]))
        except Exception as exc:  # noqa: BLE001 — failure IS the measurement
            with lock:
                failed.append(f"{type(exc).__name__}: {exc}")

    threads = []
    for _ in range(n_requests):
        tokens = [int(t) for t in rng.integers(1, 90, rng.integers(4, 17))]
        t = threading.Thread(target=one_request, args=(tokens,))
        t.start()
        threads.append(t)
        time.sleep(float(rng.exponential(1.0 / max(rate_hz, 1e-6))))
    return {"threads": threads, "ok": ok, "failed": failed}


async def _pool_rpc(port: int, payload: dict) -> dict:
    r, w = await asyncio.open_connection("127.0.0.1", port)
    await send_request(w, RequestType.POOL_BORROW, payload)
    msg = await recv_msg(r)
    w.close()
    return msg


async def _wait_all(fleet, verb: str, *, match=None) -> None:
    for a in fleet:
        deadline = time.monotonic() + PHASE_TIMEOUT_S
        while time.monotonic() < deadline:
            hits = [m for m in a.inbox if m.get("kind") == verb
                    and (match is None or match(m))]
            if hits:
                break
            await asyncio.sleep(0.01)
        else:
            raise TimeoutError(f"{a.ip}: no {verb} broadcast")


def _percentile(hist, q: float):
    merged = metrics.merge_histogram_series(hist.series())
    if merged is None:
        return None
    v = metrics.histogram_percentile(merged, q)
    return round(v, 6) if v is not None else None


async def _bench() -> dict:
    tmp = tempfile.mkdtemp(prefix="oobleck-pool-bench-")
    serve_tmp = tempfile.mkdtemp(prefix="oobleck-pool-bench-serve-")
    os.environ[journal_mod.ENV_STATE_DIR] = tmp
    os.environ[pool_arbiter.ENV_POOL] = "1"

    # The peak rate is a chaos fault, not a bench constant: the same
    # directive grammar drives sim and chaos runs.
    wave_spec = os.environ.get("OOBLECK_CHAOS") or DEFAULT_WAVE
    c = chaos_mod.reset(wave_spec)
    wave = None
    for _ in range(64):  # @<poll> delays activate within the first polls
        wave = c.traffic_wave()
        if wave is not None:
            break
    assert wave is not None, "no traffic_wave directive active"
    peak_rps, period_s = wave
    trough_rps = max(peak_rps / 8.0, 1.0)

    # -- training tenant: journaling master + scripted fleet ------------ #
    args = OobleckArguments()
    args.dist.node_ips = list(AGENTS)
    m, mtask = await _start_master(0)
    port = m.port
    r, w = await asyncio.open_connection("127.0.0.1", port)
    await send_request(w, RequestType.LAUNCH_JOB, {"args": args.to_dict()})
    assert (await recv_msg(r))["kind"] == ResponseType.SUCCESS.value
    w.close()
    fleet = [ScriptedAgent(ip) for ip in AGENTS]
    for a in fleet:
        await a.register(port)

    # -- serve tenant: real plane, tiny model --------------------------- #
    import jax

    from oobleck_tpu.models import build_model
    from oobleck_tpu.serve import ServeArguments, ServingPlane, publish_params

    model = build_model("gpt2-tiny", {"num_layers": 2})
    params = model.init_params(jax.random.PRNGKey(0))
    publish_params(serve_tmp, model, params, step=1, model_name="gpt2-tiny")
    plane = ServingPlane(
        serve_tmp, model=model,
        args=ServeArguments(port=0, slots=2, max_seq=64, reload_secs=0.5,
                            page_size=16, kv_pages=32,
                            lanes=SERVE_LANES)).start()
    sport = plane.server.port
    # Tight thresholds so the tiny plane's peak registers as pressure.
    monitor = PressureMonitor(queue_high=2.0, hysteresis=1)

    try:
        # Off-peak baseline: trough traffic must NOT pressure.
        base = _fire_wave(sport, n_requests=2, rate_hz=trough_rps,
                          gen_tokens=GEN_TOKENS, seed=1)
        for t in base["threads"]:
            await asyncio.to_thread(t.join)
        baseline = monitor.sample()
        baseline_pressured = monitor.pressured

        # Peak: fire the wave, sample pressure mid-flight.
        peak_task = asyncio.create_task(asyncio.to_thread(
            _fire_wave, sport, n_requests=PEAK_REQUESTS, rate_hz=peak_rps,
            gen_tokens=GEN_TOKENS, seed=2))
        pressure = None
        deadline = time.monotonic() + PHASE_TIMEOUT_S
        while time.monotonic() < deadline:
            monitor.sample()
            if monitor.pressured \
                    and monitor.slo_debt_s(LEASE_TTL_S) >= MIN_DEBT_S:
                pressure = monitor.as_payload(horizon_s=LEASE_TTL_S)
                break
            await asyncio.sleep(0.02)
        assert pressure is not None, "serve never pressured under the peak"

        # Borrow: the pressure payload IS the request.
        t0 = time.monotonic()
        msg = await _pool_rpc(port, {
            TENANT_KEY: "serve-bench", "chips": 1, "pressure": pressure,
            "slo": {"ttft_p99_s": monitor.ttft_slo_s},
            "lease_ttl_s": LEASE_TTL_S, "cause": "traffic_wave_peak"})
        borrow_latency = time.monotonic() - t0
        assert msg["kind"] == ResponseType.SUCCESS.value, msg
        lease = msg[LEASE_KEY]
        decision = msg[DECISION_KEY]
        victim_ip = lease["hosts"][0]
        await _wait_all(fleet, ResponseType.LEASE_GRANT.value)
        grant_broadcast = time.monotonic() - t0

        # The victim drains: clean exit, and the fleet must see ZERO
        # recovery verbs — a lease is not a failure.
        victim_clean = m.agents[victim_ip].clean_exit
        assert victim_clean
        victim = next(a for a in fleet if a.ip == victim_ip)
        victim.close()
        survivors = [a for a in fleet if a.ip != victim_ip]
        await asyncio.sleep(0.3)
        recovery_verbs = {ResponseType.RECONFIGURATION.value,
                          ResponseType.DEGRADE.value,
                          ResponseType.RESTORE.value}
        recoveries = [x for a in fleet for x in a.inbox
                      if x.get("kind") in recovery_verbs]
        retention = (len(AGENTS) - len(lease["hosts"])) / len(AGENTS)

        # Drain the peak; every request must have completed.
        peak = await peak_task
        for t in peak["threads"]:
            await asyncio.to_thread(t.join)
        issued = len(peak["ok"]) + len(peak["failed"])
        attainment = len(peak["ok"]) / max(issued, 1)

        # Off-peak: pressure clears, serve releases, chips ride the
        # grow path home.
        off = monitor.sample()
        t0 = time.monotonic()
        msg = await _pool_rpc(port, {
            TENANT_KEY: "serve-bench", "release": lease["lease_id"],
            "pressure": monitor.as_payload(horizon_s=LEASE_TTL_S)})
        assert msg["kind"] == ResponseType.SUCCESS.value, msg
        await _wait_all(
            survivors, ResponseType.LEASE_RECLAIM.value,
            match=lambda x: x.get(LEASE_KEY, {}).get("lease_id")
            == lease["lease_id"])
        reclaim_broadcast = time.monotonic() - t0
        reclaim_msg = next(
            x for x in survivors[0].inbox
            if x.get("kind") == ResponseType.LEASE_RECLAIM.value)

        goodput_cost = m.pool.tenants.incident_cost(decision["trace_id"]) \
            if m.pool is not None else None

        b = plane.batcher
        return {
            "wave": {"spec": wave_spec, "peak_rps": peak_rps,
                     "period_s": period_s, "trough_rps": trough_rps},
            "train_hosts": len(AGENTS),
            "chips_borrowed": len(lease["hosts"]),
            "baseline": {"pressured": baseline_pressured,
                         "score": baseline["score"]},
            "pressure_at_borrow": pressure,
            "borrow": {
                "mechanism": decision["mechanism"],
                "borrow_latency_s": round(borrow_latency, 6),
                "grant_broadcast_s": round(grant_broadcast, 6),
                "lease_id": lease["lease_id"],
                "victim": victim_ip,
            },
            "serve_peak": {
                "requests": issued,
                "failed": len(peak["failed"]),
                "attainment": round(attainment, 4),
                "ttft_p99_s": _percentile(b.m_ttft, 0.99),
                "tokens": int(sum(peak["ok"])),
            },
            "training_yield": {
                "goodput_retention": round(retention, 4),
                "recovery_broadcasts": len(recoveries),
                "respawns": 0 if victim_clean else 1,
                "per_tenant_goodput_cost_s": goodput_cost,
            },
            "reclaim": {
                "via": "grow",
                "release_to_reclaim_broadcast_s": round(reclaim_broadcast, 6),
                "returned_hosts": reclaim_msg.get(JOINED_KEY),
                "offpeak_score": off["score"],
            },
            "note": ("scripted training fleet over real TCP + real serve "
                     "plane on a tiny model; the peak rate is the chaos "
                     "traffic_wave directive, attainment counts every "
                     "peak-phase request"),
        }
    finally:
        plane.stop()
        # Hard-kill first: journaling stops before the state dir goes
        # away, so late agent-close callbacks cannot race the rmtree.
        _hard_kill(m)
        mtask.cancel()
        await m.stop()
        for a in fleet:
            a.close()
        shutil.rmtree(serve_tmp, ignore_errors=True)
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> None:
    print(json.dumps(asyncio.run(_bench())))


if __name__ == "__main__":
    main()
