"""Tenant registry: who shares the pool, and where their seconds went.

A tenant is one training job or one serve replica group, described by a
priority and an SLO descriptor. The registry also owns one attributed
goodput ledger (obs/goodput.py) PER tenant — the PR-17 single-job
ledger, multiplied — so cross-tenant arbitration can answer the only
question that justifies it: whose seconds did this decision spend?
``attribute`` charges one arbiter incident across several tenants in
one call (borrower gains are the lender's recovery seconds), and
``incident_cost`` returns the per-tenant breakdown that lands in the
incident file's ``goodput_cost`` section.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from oobleck_tpu.obs.goodput import GoodputLedger

KIND_TRAIN = "train"
KIND_SERVE = "serve"


@dataclass
class TenantSpec:
    """One pool tenant: a training job or a serve replica group."""

    name: str
    kind: str = KIND_TRAIN          # "train" | "serve"
    priority: int = 0               # higher preempts lower at equal cost
    # SLO descriptor: serve tenants carry e.g. {"ttft_p99_s": 2.0};
    # training tenants e.g. {"min_hosts": 1}. Free-form — the arbiter
    # reads the keys it knows and carries the rest for forensics.
    slo: dict = field(default_factory=dict)

    def as_record(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "priority": self.priority,
            "slo": dict(self.slo),
        }


class TenantRegistry:
    """Tenant specs + per-tenant goodput ledgers for one pool.

    Single-writer (the master's event loop / one sim run); the ledgers
    themselves are thread-safe for the feeds that cross threads."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._specs: dict[str, TenantSpec] = {}
        self._ledgers: dict[str, GoodputLedger] = {}

    # -- membership ---------------------------------------------------------- #

    def register(self, spec: TenantSpec) -> TenantSpec:
        """Idempotent by name: re-registering updates the descriptor but
        keeps the tenant's ledger (its wall-clock history is real)."""
        self._specs[spec.name] = spec
        self._ledgers.setdefault(spec.name, GoodputLedger(clock=self._clock))
        return spec

    def get(self, name: str) -> TenantSpec | None:
        return self._specs.get(name)

    def names(self) -> list[str]:
        return sorted(self._specs)

    def ledger(self, name: str) -> GoodputLedger:
        """The tenant's ledger, creating tenant-less bookkeeping on first
        touch — attribution must never be dropped because registration
        raced the incident."""
        if name not in self._ledgers:
            self._ledgers[name] = GoodputLedger(clock=self._clock)
        return self._ledgers[name]

    # -- cross-tenant attribution -------------------------------------------- #

    def attribute(self, trace_id: str, charges: dict[str, float], *,
                  bucket: str = "recovery", cause: str = "") -> None:
        """Charge one arbiter incident across tenants: ``charges`` maps
        tenant -> seconds, each entering THAT tenant's ledger under the
        shared trace id, so every tenant's buckets still sum to its own
        wall while the incident file can total the cross-tenant bill."""
        for tenant, seconds in charges.items():
            self.ledger(tenant).attribute(
                trace_id, seconds, bucket=bucket, cause=cause)

    def incident_cost(self, trace_id: str) -> dict | None:
        """Per-tenant ``goodput_cost`` breakdown for one incident file:
        {tenant: {lost_s, buckets, cause}}, or None when no ledger holds
        a charge for the trace."""
        out = {}
        for tenant in sorted(self._ledgers):
            cost = self._ledgers[tenant].incident_cost(trace_id)
            if cost is not None:
                out[tenant] = cost
        return out or None

    # -- /status ------------------------------------------------------------- #

    def snapshot(self) -> dict:
        """Tenant block for /status: descriptor + ledger digest each."""
        out = {}
        for name in self.names():
            ledger = self._ledgers[name]
            led = ledger.snapshot()
            out[name] = {
                **self._specs[name].as_record(),
                "wall_s": led["wall_s"],
                "goodput_fraction": led["goodput_fraction"],
                "buckets": led["buckets"],
                "incidents": len(led["incidents"]),
            }
        return out
