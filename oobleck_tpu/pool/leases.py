"""Chip leases: the unit of cross-tenant chip movement.

A lease names WHO borrowed WHICH hosts from WHOM and until WHEN. Leases
are deliberately time-bounded — a lease that never ends is an
allocation, and the pool's whole point is that peaks pass. Expiry does
not end a lease by itself: the sweep surfaces due leases to the arbiter,
which scores hold-vs-reclaim (a borrower still under live pressure can
win an extension; an expired lease makes `hold` infeasible, so the
chips flow back through the grow path).

Every transition is a journal entry (elastic/journal.py EV_LEASE), so a
restarted master still knows who holds whose chips — the lease book
restores from the replayed snapshot and the sweep picks up exactly
where the dead incarnation left off.

Timestamps are wall-clock (``time.time``): expiry must survive a master
restart, and monotonic clocks do not.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

# Lease lifecycle states. "active" is the only state journaled as live;
# the terminal states record WHY the lease ended in the transition entry.
ST_ACTIVE = "active"
ST_RETURNED = "returned"    # borrower released early (peak passed)
ST_RECLAIMED = "reclaimed"  # arbiter reclaimed (off-peak sweep)
ST_EXPIRED = "expired"      # TTL ran out with no extension


@dataclass
class ChipLease:
    """One grant of `hosts` from `lender` to `tenant` until `expires_at`."""

    lease_id: str
    tenant: str                 # borrower
    lender: str                 # whose chips these are
    hosts: list[str]
    granted_at: float           # wall ts
    expires_at: float           # wall ts
    state: str = ST_ACTIVE
    trace_id: str = ""          # arbiter incident that granted it

    def remaining_s(self, now: float) -> float:
        return max(self.expires_at - now, 0.0)

    def expired(self, now: float) -> bool:
        return now >= self.expires_at

    def as_record(self) -> dict:
        """The dict that rides LEASE_KEY on the wire and /status."""
        return {
            "lease_id": self.lease_id,
            "tenant": self.tenant,
            "lender": self.lender,
            "hosts": list(self.hosts),
            "granted_at": round(self.granted_at, 6),
            "expires_at": round(self.expires_at, 6),
            "state": self.state,
            "trace_id": self.trace_id,
        }


class LeaseBook:
    """Active leases for one pool, with monotonic ids and journal restore.

    Single-writer like the rest of the master's state: the master's
    event loop serializes every transition (same contract as the
    registry / policy engine), so no lock."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._leases: dict[str, ChipLease] = {}
        self._seq = 0
        self._granted = 0
        self._ended: dict[str, int] = {}  # terminal state -> count

    # -- transitions -------------------------------------------------------- #

    def grant(self, tenant: str, hosts: list[str], ttl_s: float, *,
              lender: str = "default", trace_id: str = "") -> ChipLease:
        self._seq += 1
        now = self._clock()
        lease = ChipLease(
            lease_id=f"lease-{self._seq}",
            tenant=tenant,
            lender=lender,
            hosts=list(hosts),
            granted_at=now,
            expires_at=now + max(float(ttl_s), 0.0),
            trace_id=trace_id,
        )
        self._leases[lease.lease_id] = lease
        self._granted += 1
        return lease

    def end(self, lease_id: str, state: str = ST_RETURNED
            ) -> ChipLease | None:
        """Terminal transition: the lease leaves the active book. Returns
        the ended lease (state updated) or None if unknown/already ended."""
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return None
        lease.state = state
        self._ended[state] = self._ended.get(state, 0) + 1
        return lease

    def extend(self, lease_id: str, ttl_s: float) -> ChipLease | None:
        """Push an active lease's expiry out by `ttl_s` from now (the
        arbiter chose `hold` for a borrower still under pressure)."""
        lease = self._leases.get(lease_id)
        if lease is None:
            return None
        lease.expires_at = self._clock() + max(float(ttl_s), 0.0)
        return lease

    # -- reads -------------------------------------------------------------- #

    def get(self, lease_id: str) -> ChipLease | None:
        return self._leases.get(lease_id)

    def active(self) -> list[ChipLease]:
        return sorted(self._leases.values(), key=lambda le: le.lease_id)

    def due(self, now: float | None = None) -> list[ChipLease]:
        """Active leases whose TTL has run out — the sweep feeds these to
        the arbiter; nothing ends until the arbiter says so."""
        t = self._clock() if now is None else now
        return [le for le in self.active() if le.expired(t)]

    def leased_hosts(self) -> set[str]:
        """Hosts currently out on any active lease."""
        out: set[str] = set()
        for lease in self._leases.values():
            out.update(lease.hosts)
        return out

    def find_by_host(self, host: str) -> ChipLease | None:
        for lease in self.active():
            if host in lease.hosts:
                return lease
        return None

    def snapshot(self) -> dict:
        """Bounded lease view for the /status pool block."""
        return {
            "active": [le.as_record() for le in self.active()],
            "granted_total": self._granted,
            "ended": dict(sorted(self._ended.items())),
        }

    # -- journal restore ----------------------------------------------------- #

    def restore(self, journal_leases: dict) -> None:
        """Rehydrate active leases from the replayed journal state
        (elastic/journal.py state["leases"]). The id counter resumes past
        the highest restored suffix so a restarted master never reissues
        a lease id a dead incarnation already granted."""
        for lease_id, rec in sorted((journal_leases or {}).items()):
            if not isinstance(rec, dict):
                continue
            lease = ChipLease(
                lease_id=str(lease_id),
                tenant=str(rec.get("tenant") or "default"),
                lender=str(rec.get("lender") or "default"),
                hosts=[str(h) for h in (rec.get("hosts") or [])],
                granted_at=float(rec.get("ts") or 0.0),
                expires_at=float(rec.get("expires_at") or 0.0),
            )
            self._leases[lease.lease_id] = lease
            suffix = lease.lease_id.rpartition("-")[2]
            if suffix.isdigit():
                self._seq = max(self._seq, int(suffix))
