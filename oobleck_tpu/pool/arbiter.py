"""PoolArbiter: one auditable decision per cross-tenant incident.

The pool's decision engine is deliberately the policy plane's shape
(policy/engine.py) pointed across tenants: build arms, score them with
the SAME churn-aware cost model — extended with the cross-tenant
SLO-debt and preemption-cost terms — pick the cheapest feasible, record
the roads not taken. A serve traffic peak is an *incident* exactly like
a host loss or a JOIN: classify (borrow vs reclaim direction), score,
broadcast — so the forensics, forced-mode baselines, and the
projected-vs-measured feedback loop all come for free.

Two deliberate asymmetries with the single-tenant engine:

* The amortization horizon is the LEASE, not the MTBF: a borrow's
  degraded-training term runs until the lease ends (the chips come
  back), so a short lease makes borrowing cheap and a long one makes
  the arbiter think twice — ``score_arms(mtbf_s=lease_ttl)``.
* ``deny`` (borrow) and ``reclaim_grow`` (reclaim) are the directions'
  always-feasible fallbacks: the arbiter can always say no, and an
  ended lease can always flow back through the grow path.

``OOBLECK_POOL_POLICY=deny|borrow_spare|borrow_drain|hold|reclaim_grow``
forces a fixed arm (benchmark baselines, same contract as
``OOBLECK_POLICY``); a forced arm pins only its own direction.
"""

from __future__ import annotations

import collections
import logging
import os
import time
from dataclasses import dataclass, field

from oobleck_tpu.obs import spans
from oobleck_tpu.policy.scorer import cheapest_feasible, score_arms
from oobleck_tpu.policy.signals import build_borrow_arms, build_reclaim_arms
from oobleck_tpu.pool.leases import ChipLease, LeaseBook
from oobleck_tpu.pool.tenants import TenantRegistry
from oobleck_tpu.utils import metrics

logger = logging.getLogger("oobleck.pool")

ENV_POOL = "OOBLECK_POOL"                      # "1" enables the plane
ENV_POOL_POLICY = "OOBLECK_POOL_POLICY"        # forced arm
ENV_POOL_TENANT = "OOBLECK_POOL_TENANT"        # training tenant's name
ENV_LEASE_TTL = "OOBLECK_POOL_LEASE_TTL_S"
ENV_MIN_TRAIN_HOSTS = "OOBLECK_POOL_MIN_TRAIN_HOSTS"
ENV_SWEEP = "OOBLECK_POOL_SWEEP_S"             # lease-sweep cadence

DEFAULT_LEASE_TTL_S = 60.0
DEFAULT_MIN_TRAIN_HOSTS = 1
DEFAULT_SWEEP_S = 5.0

# Borrow-direction arms.
MECH_DENY = "deny"
MECH_BORROW_SPARE = "borrow_spare"
MECH_BORROW_DRAIN = "borrow_drain"
# Reclaim-direction arms.
MECH_HOLD = "hold"
MECH_RECLAIM_GROW = "reclaim_grow"
MODE_ADAPTIVE = "adaptive"
BORROW_MODES = (MECH_DENY, MECH_BORROW_SPARE, MECH_BORROW_DRAIN)
RECLAIM_MODES = (MECH_HOLD, MECH_RECLAIM_GROW)
POOL_MODES = (MODE_ADAPTIVE,) + BORROW_MODES + RECLAIM_MODES

# Decisions kept for /status (bounded like the policy engine's log).
MAX_DECISIONS = 16
EWMA_ALPHA = 0.5


def pool_enabled() -> bool:
    """Whether the pool plane is on (``OOBLECK_POOL=1``). Inert default:
    a single-job cluster keeps its exact pre-pool behavior."""
    return os.environ.get(ENV_POOL, "").strip() == "1"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def sweep_period_s() -> float:
    """Lease-sweep cadence for the master's reclaim loop (floored so a
    zero/garbage knob cannot spin the event loop)."""
    return max(_env_float(ENV_SWEEP, DEFAULT_SWEEP_S), 0.05)


@dataclass
class PoolDecision:
    """What the arbiter chose for one cross-tenant incident."""

    direction: str                  # "borrow" | "reclaim"
    mechanism: str
    tenant: str                     # the requesting / borrowing tenant
    lender: str = "default"
    chips: int = 0
    hosts: list = field(default_factory=list)   # filled when granted
    lease_id: str = ""
    reason: str = "cheapest"
    projected_cost_s: float | None = None
    measured_s: float | None = None
    costs: dict = field(default_factory=dict)
    infeasible: dict = field(default_factory=dict)
    arms: dict = field(default_factory=dict)
    slo_debt_s: float = 0.0
    horizon_s: float | None = None  # lease lifetime the scoring amortized
    trace_id: str | None = None
    decided_at: float = field(default_factory=time.time)

    def as_payload(self) -> dict:
        """Compact dict for the POOL_BORROW answer and /status log."""
        return {
            "direction": self.direction,
            "mechanism": self.mechanism,
            "tenant": self.tenant,
            "lender": self.lender,
            "chips": self.chips,
            "hosts": list(self.hosts),
            "lease_id": self.lease_id,
            "reason": self.reason,
            "projected_cost_s": self.projected_cost_s,
            "measured_s": self.measured_s,
            "costs": {m: round(c, 6) for m, c in self.costs.items()},
            "infeasible": dict(self.infeasible),
            "slo_debt_s": round(self.slo_debt_s, 6),
            "horizon_s": self.horizon_s,
            "trace_id": self.trace_id,
            "decided_at": self.decided_at,
        }

    def as_record(self) -> dict:
        rec = self.as_payload()
        rec["arms"] = dict(self.arms)
        return rec

    def record(self) -> None:
        """Flight-record the decision and bump the oobleck_pool_* family
        in one call, so the two views cannot disagree."""
        metrics.flight_recorder().record("pool_decision", **self.as_record())
        reg = metrics.registry()
        reg.counter(
            "oobleck_pool_decisions_total",
            "Pool-arbiter decisions by direction, mechanism and reason",
        ).inc(direction=self.direction, mechanism=self.mechanism,
              reason=self.reason)
        if self.projected_cost_s is not None:
            reg.gauge(
                "oobleck_pool_projected_cost_seconds",
                "Projected cost of the last pool-arbiter decision",
            ).set(self.projected_cost_s, mechanism=self.mechanism)


class PoolArbiter:
    """Cross-tenant decision engine + the tenant/lease state it arbitrates.

    Owns a TenantRegistry and a LeaseBook (both injectable — the sim
    passes its own clock so runs are hermetic); the master owns the
    wire, the journal entries, and the broadcasts."""

    def __init__(self, *, tenants: TenantRegistry | None = None,
                 leases: LeaseBook | None = None,
                 registry=None, clock=time.time,
                 mode: str | None = None,
                 lease_ttl_s: float | None = None,
                 min_train_hosts: int | None = None,
                 priors_path: str | None = None):
        if mode is None:
            mode = os.environ.get(ENV_POOL_POLICY, "").strip().lower()
        self.mode = mode or MODE_ADAPTIVE
        if self.mode not in POOL_MODES:
            raise ValueError(
                f"bad {ENV_POOL_POLICY}={self.mode!r}: "
                f"want one of {POOL_MODES}")
        self.clock = clock
        self.tenants = tenants if tenants is not None else TenantRegistry()
        self.leases = leases if leases is not None else LeaseBook(clock=clock)
        self.lease_ttl_s = (float(lease_ttl_s) if lease_ttl_s is not None
                            else _env_float(ENV_LEASE_TTL,
                                            DEFAULT_LEASE_TTL_S))
        self.min_train_hosts = (int(min_train_hosts)
                                if min_train_hosts is not None
                                else int(_env_float(
                                    ENV_MIN_TRAIN_HOSTS,
                                    DEFAULT_MIN_TRAIN_HOSTS)))
        self._registry = registry
        self._priors_path = priors_path
        self._ewma: dict[str, float] = {}
        self._decisions: collections.deque = collections.deque(
            maxlen=MAX_DECISIONS)

    # -- feedback ------------------------------------------------------------ #

    def observe_measured(self, mechanism: str, seconds: float) -> None:
        """Feed one measured borrow/reclaim latency: updates the EWMA the
        next decision scores with and closes the projected-vs-measured
        loop on the latest matching decision."""
        prev = self._ewma.get(mechanism)
        self._ewma[mechanism] = (seconds if prev is None else
                                 (1 - EWMA_ALPHA) * prev
                                 + EWMA_ALPHA * seconds)
        reg = self._registry or metrics.registry()
        reg.histogram(
            "oobleck_pool_measured_seconds",
            "Measured borrow/reclaim latency by mechanism (pool feedback)",
        ).observe(seconds, mechanism=mechanism)
        for d in reversed(self._decisions):
            if d.mechanism == mechanism and d.measured_s is None:
                d.measured_s = seconds
                break

    # -- decisions ----------------------------------------------------------- #

    def decide_borrow(self, tenant: str, chips: int, *,
                      train_hosts: int,
                      spare_hosts: int = 0,
                      slo_debt_s: float = 0.0,
                      lease_ttl_s: float | None = None,
                      drain_cost_s: float | None = None,
                      lender: str = "default",
                      cause: str = "pressure") -> PoolDecision:
        """Score the BORROW arms for one pressure incident and pick.

        ``slo_debt_s`` is the requester's priced pressure (it rides the
        arms that leave it unrelieved); ``lease_ttl_s`` is the horizon
        the degraded-training term amortizes over — the lease IS the
        amortization window, because the chips come back when it ends."""
        ttl = float(lease_ttl_s) if lease_ttl_s is not None \
            else self.lease_ttl_s
        with spans.span("pool.decide_borrow", tenant=tenant,
                        chips=str(chips), cause=cause) as ctx:
            arms = build_borrow_arms(
                chips=chips,
                train_hosts=train_hosts,
                spare_hosts=spare_hosts,
                min_train_hosts=self.min_train_hosts,
                slo_debt_s=slo_debt_s,
                drain_cost_s=drain_cost_s,
                latency_overrides=self._ewma,
                registry=self._registry,
                priors_path=self._priors_path,
            )
            scored = score_arms(arms, mtbf_s=ttl)
            chosen, reason = self._pick(scored, fallback=MECH_DENY)
            decision = PoolDecision(
                direction="borrow",
                mechanism=chosen.mechanism,
                tenant=tenant,
                lender=lender,
                chips=int(chips),
                reason=reason,
                projected_cost_s=chosen.cost_s,
                costs={m: a.cost_s for m, a in scored.items()},
                infeasible={m: a.reason for m, a in scored.items()
                            if not a.feasible},
                arms={m: dict(arms[m].as_record(),
                              **scored[m].as_record())
                      for m in arms},
                slo_debt_s=float(slo_debt_s),
                horizon_s=ttl,
                trace_id=ctx["trace_id"],
            )
        logger.info(
            "pool: %s for borrow of %d chip-hosts by %s "
            "(reason=%s cost=%.3fs debt=%.1fs ttl=%.0fs)",
            decision.mechanism, chips, tenant, reason, chosen.cost_s,
            slo_debt_s, ttl)
        self._decisions.append(decision)
        decision.record()
        return decision

    def decide_reclaim(self, lease: ChipLease, *,
                       train_hosts: int,
                       slo_debt_s: float = 0.0,
                       cause: str = "sweep") -> PoolDecision:
        """Score hold-vs-reclaim for one lease at its sweep/expiry/release
        point. ``slo_debt_s`` is the borrower's STILL-live pressure: it
        rides reclaim_grow (taking the chips back re-exposes the borrower
        to the peak), which is what holds through the peak and reclaims
        off-peak. An expired lease makes hold infeasible — leases end."""
        now = self.clock()
        remaining = lease.remaining_s(now)
        with spans.span("pool.decide_reclaim", tenant=lease.tenant,
                        lease_id=lease.lease_id, cause=cause) as ctx:
            arms = build_reclaim_arms(
                leased_hosts=len(lease.hosts),
                train_hosts=train_hosts,
                slo_debt_s=slo_debt_s,
                lease_expired=lease.expired(now),
                latency_overrides=self._ewma,
                registry=self._registry,
                priors_path=self._priors_path,
            )
            scored = score_arms(
                arms, mtbf_s=remaining if remaining > 0 else None)
            chosen, reason = self._pick(scored, fallback=MECH_RECLAIM_GROW)
            decision = PoolDecision(
                direction="reclaim",
                mechanism=chosen.mechanism,
                tenant=lease.tenant,
                lender=lease.lender,
                chips=len(lease.hosts),
                hosts=list(lease.hosts),
                lease_id=lease.lease_id,
                reason=reason,
                projected_cost_s=chosen.cost_s,
                costs={m: a.cost_s for m, a in scored.items()},
                infeasible={m: a.reason for m, a in scored.items()
                            if not a.feasible},
                arms={m: dict(arms[m].as_record(),
                              **scored[m].as_record())
                      for m in arms},
                slo_debt_s=float(slo_debt_s),
                horizon_s=remaining,
                trace_id=ctx["trace_id"],
            )
        logger.info(
            "pool: %s for lease %s of %s (reason=%s cost=%.3fs "
            "debt=%.1fs remaining=%.0fs)",
            decision.mechanism, lease.lease_id, lease.tenant, reason,
            chosen.cost_s, slo_debt_s, remaining)
        self._decisions.append(decision)
        decision.record()
        return decision

    def _pick(self, scored, *, fallback: str):
        """Forced-mode gate + cheapest-feasible, the policy engine's
        contract: a forced arm pins only its own direction, and an
        infeasible forced arm falls back to the direction's
        always-available mechanism with an honest reason string."""
        forced = self.mode if self.mode in scored else MODE_ADAPTIVE
        if forced != MODE_ADAPTIVE:
            if scored[forced].feasible:
                return scored[forced], f"forced:{forced}"
            return scored[fallback], (f"forced:{forced}:infeasible:"
                                      f"{scored[forced].reason}")
        chosen = cheapest_feasible(scored)
        if chosen is None:  # cannot happen: deny/reclaim_grow are
            return scored[fallback], "fallback"  # always feasible
        return chosen, "cheapest"

    # -- /status ------------------------------------------------------------- #

    def status(self) -> dict:
        """The /status ``pool`` block: knobs, tenants, leases, decisions."""
        return {
            "enabled": True,
            "mode": self.mode,
            "lease_ttl_s": self.lease_ttl_s,
            "min_train_hosts": self.min_train_hosts,
            "tenants": self.tenants.snapshot(),
            "leases": self.leases.snapshot(),
            "decisions": [d.as_payload() for d in self._decisions],
        }
