"""Shared chip-pool arbiter: multi-tenant leases over one finite pool.

The elastic planes below this package (templates, grow incidents,
policy arms, proactive drain) each serve exactly ONE training job; this
package is the cross-tenant layer that lets several jobs and the serve
plane negotiate who restores, who degrades, and who yields chips:

    tenants.py   tenant registry (training jobs + serve replica groups,
                 each with a priority/SLO descriptor) and per-tenant
                 attributed goodput ledgers
    leases.py    chip leases with expiry — the unit of cross-tenant
                 chip movement, journaled so a restarted master still
                 knows who holds whose chips
    pressure.py  serve-side pressure monitor (queue depth, TTFT p99,
                 deadline_queued rate) that turns traffic peaks into
                 borrow requests with an SLO-debt price attached
    arbiter.py   the pool decision engine: borrow/reclaim arms scored
                 through the SAME classify->score->broadcast chain as
                 every other incident (policy/scorer.py, extended with
                 cross-tenant SLO-debt and preemption-cost terms)
    bench.py     `make pool-bench`: a real master + agents + serving
                 plane driven through a full borrow/return cycle by a
                 chaos `traffic_wave`

The pool plane is inert unless ``OOBLECK_POOL=1``: a single-job cluster
pays one env read and keeps its exact pre-pool behavior.
"""

from oobleck_tpu.pool.arbiter import PoolArbiter, PoolDecision
from oobleck_tpu.pool.leases import ChipLease, LeaseBook
from oobleck_tpu.pool.pressure import PressureMonitor
from oobleck_tpu.pool.tenants import TenantRegistry, TenantSpec

__all__ = [
    "ChipLease",
    "LeaseBook",
    "PoolArbiter",
    "PoolDecision",
    "PressureMonitor",
    "TenantRegistry",
    "TenantSpec",
]
