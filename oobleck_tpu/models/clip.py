"""CLIP dual-encoder as an explicit layer list with contrastive loss.

Capability match for the reference's clip family (listed in its tested image
models, /root/reference/oobleck/module/model.py:21-33; like swin, the
reference's fx splitter has no clip branch — sharding.py:12-47 — so this
EXCEEDS the reference, which would assert on clip).

Layer list runs the two towers in sequence, so pipeline stages are still
contiguous layer ranges:
    [img_embed, img_block_0.., img_pool, txt_embed, txt_block_0.., head]
The image tower's pooled projection rides the carry through the text tower
as a (img_emb, txt_x) pair — the same mid-pipeline batch-consumer pattern
as T5's bridge (models/t5.py): `txt_embed` reads batch["input_ids"], so
batch_layers lists it for stage placement.

Objective: in-batch symmetric contrastive loss (logits = scale * img @ txt.T,
cross-entropy against the diagonal in both directions). With microbatching,
negatives are per-microbatch — the standard data-parallel CLIP behavior
without cross-device gather; documented, not hidden.

Architecture notes: ViT-style image tower (class token, pre-norm blocks),
causal text tower pooled at the final position, learned logit scale
(clamped at exp(4.6) ~ 100 like OpenAI CLIP).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp

from oobleck_tpu.models.gpt import _layer_norm
from oobleck_tpu.ops.attention import _xla_causal_attention


@dataclass(frozen=True)
class CLIPConfig:
    # vision tower
    image_size: int = 224
    patch_size: int = 32
    num_channels: int = 3
    vision_hidden_size: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    # text tower
    vocab_size: int = 49408
    max_position_embeddings: int = 77
    text_hidden_size: int = 512
    text_layers: int = 12
    text_heads: int = 8
    # shared
    projection_dim: int = 512
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    logit_scale_init: float = 2.6592  # ln(1/0.07), OpenAI CLIP default
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def override(self, **kwargs) -> "CLIPConfig":
        unknown = [k for k in kwargs if k not in CLIPConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        return replace(self, **kwargs)


def _init_tx_block(rng, e: int, h: int, std: float, param_dtype):
    f = 4 * e
    ks = jax.random.split(rng, 4)
    return {
        "ln1": {"scale": jnp.ones((e,), param_dtype),
                "bias": jnp.zeros((e,), param_dtype)},
        "attn": {
            "wqkv": jax.random.normal(ks[0], (e, 3, h, e // h), param_dtype) * std,
            "bqkv": jnp.zeros((3, h, e // h), param_dtype),
            "wo": jax.random.normal(ks[1], (h, e // h, e), param_dtype) * std,
            "bo": jnp.zeros((e,), param_dtype),
        },
        "ln2": {"scale": jnp.ones((e,), param_dtype),
                "bias": jnp.zeros((e,), param_dtype)},
        "mlp": {
            "wi": jax.random.normal(ks[2], (e, f), param_dtype) * std,
            "bi": jnp.zeros((f,), param_dtype),
            "wo": jax.random.normal(ks[3], (f, e), param_dtype) * std,
            "bo": jnp.zeros((e,), param_dtype),
        },
    }


def _apply_tx_block(p, x, *, causal: bool, eps: float, dtype):
    h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], eps)
    qkv = jnp.einsum("bse,ethd->tbhsd", h, p["attn"]["wqkv"].astype(dtype))
    qkv = qkv + p["attn"]["bqkv"].astype(dtype)[:, None, :, None, :]
    attn = _xla_causal_attention(qkv[0], qkv[1], qkv[2], causal=causal)
    out = jnp.einsum("bhsd,hde->bse", attn, p["attn"]["wo"].astype(dtype))
    x = x + out + p["attn"]["bo"].astype(dtype)
    h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"], eps)
    h = jax.nn.gelu(h @ p["mlp"]["wi"].astype(dtype) + p["mlp"]["bi"].astype(dtype))
    return x + h @ p["mlp"]["wo"].astype(dtype) + p["mlp"]["bo"].astype(dtype)


class CLIPModel:
    data_kind = "contrastive"

    def __init__(self, config: CLIPConfig):
        self.config = config

    # ---- layer list ----

    @property
    def _txt_embed_index(self) -> int:
        return 1 + self.config.vision_layers + 1

    @property
    def batch_layers(self) -> set[int]:
        """img_embed reads pixel_values; txt_embed reads input_ids
        mid-pipeline; the head needs no batch (diagonal targets)."""
        return {0, self._txt_embed_index, self.num_pipeline_layers - 1}

    @property
    def num_pipeline_layers(self) -> int:
        c = self.config
        return 1 + c.vision_layers + 1 + 1 + c.text_layers + 1

    def layer_name(self, index: int) -> str:
        c = self.config
        if index == 0:
            return "img_embed"
        if index <= c.vision_layers:
            return f"img_block_{index - 1}"
        if index == c.vision_layers + 1:
            return "img_pool"
        if index == self._txt_embed_index:
            return "txt_embed"
        if index < self.num_pipeline_layers - 1:
            return f"txt_block_{index - self._txt_embed_index - 1}"
        return "head"

    def init_layer(self, rng, index):
        c = self.config
        name = self.layer_name(index)
        ks = jax.random.split(rng, 6)
        std = c.initializer_range
        if name == "img_embed":
            return self._init_img_embed(ks[0])
        if name.startswith("img_block"):
            return _init_tx_block(
                jax.random.fold_in(ks[1], index), c.vision_hidden_size,
                c.vision_heads, std, c.param_dtype)
        if name == "img_pool":
            return {
                "ln_post": {"scale": jnp.ones((c.vision_hidden_size,), c.param_dtype),
                            "bias": jnp.zeros((c.vision_hidden_size,), c.param_dtype)},
                "proj": jax.random.normal(
                    ks[2], (c.vision_hidden_size, c.projection_dim),
                    c.param_dtype) * std,
            }
        if name == "txt_embed":
            k1, k2 = jax.random.split(ks[3])
            return {
                "wte": jax.random.normal(
                    k1, (c.vocab_size, c.text_hidden_size), c.param_dtype) * std,
                "wpe": jax.random.normal(
                    k2, (c.max_position_embeddings, c.text_hidden_size),
                    c.param_dtype) * std,
            }
        if name.startswith("txt_block"):
            return _init_tx_block(
                jax.random.fold_in(ks[4], index), c.text_hidden_size,
                c.text_heads, std, c.param_dtype)
        return {
            "ln_final": {"scale": jnp.ones((c.text_hidden_size,), c.param_dtype),
                         "bias": jnp.zeros((c.text_hidden_size,), c.param_dtype)},
            "proj": jax.random.normal(
                ks[5], (c.text_hidden_size, c.projection_dim),
                c.param_dtype) * std,
            "logit_scale": jnp.asarray(c.logit_scale_init, c.param_dtype),
        }

    def apply_layer(self, index, params, carry, batch, ctx=None):
        c = self.config
        name = self.layer_name(index)
        eps = c.layer_norm_epsilon
        if name == "img_embed":
            return self.img_embed(params, batch["pixel_values"])
        if name.startswith("img_block"):
            return _apply_tx_block(params, carry, causal=False, eps=eps,
                                   dtype=c.dtype)
        if name == "img_pool":
            cls = _layer_norm(carry[:, 0], params["ln_post"]["scale"],
                              params["ln_post"]["bias"], eps)
            return cls @ params["proj"].astype(c.dtype)
        if name == "txt_embed":
            tokens = batch["input_ids"]
            x = (params["wte"][tokens]
                 + params["wpe"][: tokens.shape[-1]]).astype(c.dtype)
            return (carry, x)
        if name.startswith("txt_block"):
            img_emb, x = carry
            return (img_emb, _apply_tx_block(params, x, causal=True, eps=eps,
                                             dtype=c.dtype))
        img_emb, x = carry
        return self._similarity(params, img_emb, x)

    def _similarity(self, p, img_emb, txt_x):
        c = self.config
        x = _layer_norm(txt_x[:, -1], p["ln_final"]["scale"],
                        p["ln_final"]["bias"], c.layer_norm_epsilon)
        txt_emb = x @ p["proj"].astype(c.dtype)
        img = img_emb.astype(jnp.float32)
        txt = txt_emb.astype(jnp.float32)
        img = img / (jnp.linalg.norm(img, axis=-1, keepdims=True) + 1e-8)
        txt = txt / (jnp.linalg.norm(txt, axis=-1, keepdims=True) + 1e-8)
        scale = jnp.exp(jnp.minimum(p["logit_scale"].astype(jnp.float32), 4.6))
        return scale * img @ txt.T  # [B_img, B_txt]

    def loss_from_logits(self, logits, batch):
        """Symmetric InfoNCE against the in-batch diagonal."""
        n = logits.shape[0]
        targets = jnp.arange(n)
        logz_i = jax.nn.logsumexp(logits, axis=-1)
        logz_t = jax.nn.logsumexp(logits, axis=0)
        diag = logits[targets, targets]
        return 0.5 * (jnp.mean(logz_i - diag) + jnp.mean(logz_t - diag))

    def accuracy_from_logits(self, logits, batch):
        """In-batch image->text retrieval accuracy: the matching caption is
        the argmax of each image row (reference accuracy metric parity,
        dataset.py:39-54)."""
        n = logits.shape[0]
        correct = (jnp.argmax(logits, axis=-1) == jnp.arange(n))
        return jnp.sum(correct.astype(jnp.float32)), jnp.float32(n)

    def sample_batch(self, batch_size: int, seq_len: int | None = None):
        c = self.config
        seq = min(seq_len or c.max_position_embeddings,
                  c.max_position_embeddings)
        rng = jax.random.PRNGKey(0)
        return {
            "pixel_values": jax.random.normal(
                rng, (batch_size, c.image_size, c.image_size, c.num_channels),
                jnp.float32),
            "input_ids": jax.random.randint(
                jax.random.fold_in(rng, 1), (batch_size, seq), 0,
                c.vocab_size, dtype=jnp.int32),
        }

    # ---- init / fused views ----

    def _init_img_embed(self, rng):
        c = self.config
        k1, k2, k3 = jax.random.split(rng, 3)
        std = c.initializer_range
        patch_dim = c.patch_size * c.patch_size * c.num_channels
        return {
            "proj": jax.random.normal(
                k1, (patch_dim, c.vision_hidden_size), c.param_dtype) * std,
            "cls": jax.random.normal(
                k2, (1, 1, c.vision_hidden_size), c.param_dtype) * std,
            "pos": jax.random.normal(
                k3, (c.num_patches + 1, c.vision_hidden_size),
                c.param_dtype) * std,
            "ln_pre": {"scale": jnp.ones((c.vision_hidden_size,), c.param_dtype),
                       "bias": jnp.zeros((c.vision_hidden_size,), c.param_dtype)},
        }

    def img_embed(self, p, pixels):
        c = self.config
        b, hh, ww, ch = pixels.shape
        ps = c.patch_size
        x = pixels.reshape(b, hh // ps, ps, ww // ps, ps, ch)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, c.num_patches, ps * ps * ch)
        x = x.astype(c.dtype) @ p["proj"].astype(c.dtype)
        cls = jnp.broadcast_to(p["cls"].astype(c.dtype),
                               (b, 1, c.vision_hidden_size))
        x = jnp.concatenate([cls, x], axis=1) + p["pos"].astype(c.dtype)
        return _layer_norm(x, p["ln_pre"]["scale"], p["ln_pre"]["bias"],
                           c.layer_norm_epsilon)

    def init_params(self, rng):
        return {self.layer_name(i): self.init_layer(rng, i)
                for i in range(self.num_pipeline_layers)}

    def forward(self, params, pixel_values, input_ids):
        carry = None
        batch = {"pixel_values": pixel_values, "input_ids": input_ids}
        for i in range(self.num_pipeline_layers):
            carry = self.apply_layer(i, params[self.layer_name(i)], carry, batch)
        return carry

    def loss(self, params, batch):
        return self.loss_from_logits(
            self.forward(params, batch["pixel_values"], batch["input_ids"]),
            batch,
        )
