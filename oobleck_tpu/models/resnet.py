"""ResNet image classifier as an explicit layer list.

Capability match for the reference's resnet family
(AutoModelForImageClassification + fx split at every bottleneck block,
/root/reference/oobleck/module/model.py:26-33, sharding.py:37-41: one split
point per `resnet.encoder.stages.{i}.layers.{j}` plus the pooler).

Layer list: [stem, one layer per bottleneck block (stage-major), head] —
exactly the reference's split granularity, so templates plan over the same
units. Activations change shape across stages (spatial /2, channels x2);
the MPMD pipeline handles that naturally since every stage program is
jit-compiled for its own carry shape.

TPU-first choices:
  * NHWC layout + HWIO kernels (`lax.conv_general_dilated`) — the layout XLA
    tiles onto the MXU without transposes;
  * normalization is batch-statistics BatchNorm with trainable scale/shift
    but NO running-average state (train and eval both use batch stats):
    pipeline stages are pure functions of (params, carry), and running
    stats would be mutable cross-step state threaded through every stage.
    Deviation from HF ResNet's eval-time running stats, documented here.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ResNetConfig:
    image_size: int = 224
    num_channels: int = 3
    num_classes: int = 1000
    embedding_size: int = 64                   # stem output channels
    hidden_sizes: tuple = (256, 512, 1024, 2048)
    depths: tuple = (3, 4, 6, 3)
    reduction: int = 4                         # bottleneck squeeze factor
    initializer_range: float = 0.02
    bn_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def override(self, **kwargs) -> "ResNetConfig":
        unknown = [k for k in kwargs
                   if k not in ResNetConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        for key in ("hidden_sizes", "depths"):
            if key in kwargs:
                kwargs[key] = tuple(kwargs[key])
        return replace(self, **kwargs)


def _conv(x, w, stride: int = 1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(x, p, eps: float):
    """Batch-stats normalization over (N, H, W) with trainable scale/shift."""
    mean = jnp.mean(x, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(x, axis=(0, 1, 2), keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    return (x - mean) * inv * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)


class ResNetModel:
    # Engine contract: image batches through the generic MPMD path.
    data_kind = "image"

    def __init__(self, config: ResNetConfig):
        self.config = config
        # blocks[i] = (stage, index_in_stage) in stage-major order.
        self._blocks: list[tuple[int, int]] = [
            (s, j) for s, depth in enumerate(config.depths)
            for j in range(depth)
        ]

    # ---- layer list ----

    @property
    def num_pipeline_layers(self) -> int:
        return len(self._blocks) + 2

    def layer_name(self, index: int) -> str:
        if index == 0:
            return "stem"
        if index == self.num_pipeline_layers - 1:
            return "head"
        s, j = self._blocks[index - 1]
        return f"stage{s}_block{j}"

    def _block_shape(self, s: int, j: int) -> tuple[int, int, int]:
        """(in_channels, out_channels, stride) of block (s, j)."""
        c = self.config
        out = c.hidden_sizes[s]
        if j > 0:
            return out, out, 1
        prev = c.embedding_size if s == 0 else c.hidden_sizes[s - 1]
        return prev, out, (1 if s == 0 else 2)

    def init_layer(self, rng, index):
        ks = jax.random.split(rng, 3)
        if index == 0:
            return self._init_stem(ks[0])
        if index == self.num_pipeline_layers - 1:
            return self._init_head(ks[2])
        s, j = self._blocks[index - 1]
        return self._init_block(jax.random.fold_in(ks[1], index), s, j)

    def apply_layer(self, index, params, carry, batch, ctx=None):
        if index == 0:
            return self.stem(params, batch["pixel_values"])
        if index == self.num_pipeline_layers - 1:
            return self.head(params, carry)
        s, j = self._blocks[index - 1]
        return self.apply_block(params, carry, *self._block_shape(s, j)[2:])

    def sample_batch(self, batch_size: int, *_ignored):
        c = self.config
        rng = jax.random.PRNGKey(0)
        return {
            "pixel_values": jax.random.normal(
                rng, (batch_size, c.image_size, c.image_size, c.num_channels),
                jnp.float32,
            ),
            "labels": jax.random.randint(
                jax.random.fold_in(rng, 1), (batch_size,), 0, c.num_classes,
                dtype=jnp.int32,
            ),
        }

    # ---- init ----

    def _bn_init(self, ch: int):
        c = self.config
        return {"scale": jnp.ones((ch,), c.param_dtype),
                "bias": jnp.zeros((ch,), c.param_dtype)}

    def _conv_init(self, rng, kh, kw, cin, cout):
        c = self.config
        fan_in = kh * kw * cin
        std = (2.0 / fan_in) ** 0.5  # He init for ReLU stacks
        return jax.random.normal(rng, (kh, kw, cin, cout), c.param_dtype) * std

    def _init_stem(self, rng):
        c = self.config
        return {
            "conv": self._conv_init(rng, 7, 7, c.num_channels, c.embedding_size),
            "bn": self._bn_init(c.embedding_size),
        }

    def _init_block(self, rng, s: int, j: int):
        c = self.config
        cin, cout, stride = self._block_shape(s, j)
        mid = cout // c.reduction
        ks = jax.random.split(rng, 4)
        p = {
            "conv1": self._conv_init(ks[0], 1, 1, cin, mid),
            "bn1": self._bn_init(mid),
            "conv2": self._conv_init(ks[1], 3, 3, mid, mid),
            "bn2": self._bn_init(mid),
            "conv3": self._conv_init(ks[2], 1, 1, mid, cout),
            "bn3": self._bn_init(cout),
        }
        if cin != cout or stride != 1:
            p["shortcut"] = {
                "conv": self._conv_init(ks[3], 1, 1, cin, cout),
                "bn": self._bn_init(cout),
            }
        return p

    def _init_head(self, rng):
        c = self.config
        cout = c.hidden_sizes[-1]
        return {
            "w": jax.random.normal(rng, (cout, c.num_classes), c.param_dtype)
            * c.initializer_range,
            "b": jnp.zeros((c.num_classes,), c.param_dtype),
        }

    def init_params(self, rng):
        """Per-layer dict keyed by layer name (blocks are heterogeneous in
        shape, so there is no stacked view; the fused SPMD path does not
        apply to conv pipelines)."""
        return {self.layer_name(i): self.init_layer(rng, i)
                for i in range(self.num_pipeline_layers)}

    # ---- forward ----

    def stem(self, p, pixels):
        c = self.config
        x = pixels.astype(c.dtype)
        x = _conv(x, p["conv"].astype(c.dtype), stride=2)
        x = jax.nn.relu(_batch_norm(x, p["bn"], c.bn_epsilon))
        # 3x3 max pool, stride 2.
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
        )

    def apply_block(self, p, x, stride: int = 1):
        c = self.config
        dt = c.dtype
        h = jax.nn.relu(_batch_norm(
            _conv(x, p["conv1"].astype(dt)), p["bn1"], c.bn_epsilon))
        h = jax.nn.relu(_batch_norm(
            _conv(h, p["conv2"].astype(dt), stride=stride), p["bn2"],
            c.bn_epsilon))
        h = _batch_norm(_conv(h, p["conv3"].astype(dt)), p["bn3"], c.bn_epsilon)
        if "shortcut" in p:
            x = _batch_norm(
                _conv(x, p["shortcut"]["conv"].astype(dt), stride=stride),
                p["shortcut"]["bn"], c.bn_epsilon)
        return jax.nn.relu(x + h)

    def head(self, p, x):
        c = self.config
        pooled = jnp.mean(x, axis=(1, 2))  # global average pool
        return (pooled @ p["w"].astype(c.dtype)
                + p["b"].astype(c.dtype)).astype(jnp.float32)

    def forward(self, params, pixels):
        x = self.stem(params["stem"], pixels)
        for i, (s, j) in enumerate(self._blocks):
            name = self.layer_name(i + 1)
            block = self.apply_block
            if self.config.remat:
                block = jax.checkpoint(block, static_argnums=(2,))
            x = block(params[name], x, self._block_shape(s, j)[2])
        return self.head(params["head"], x)

    def loss_from_logits(self, logits, batch):
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, batch["labels"][..., None], axis=-1
        )[..., 0]
        return jnp.mean(logz - gold)


    def accuracy_from_logits(self, logits, batch):
        from oobleck_tpu.models.base import argmax_accuracy

        return argmax_accuracy(logits, batch["labels"])

    def loss(self, params, batch):
        return self.loss_from_logits(
            self.forward(params, batch["pixel_values"]), batch
        )
