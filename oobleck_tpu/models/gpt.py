"""GPT-2 / GPT-3 family decoder as an explicit layer list.

Capability match for the reference's gpt2 path (HF AutoModel + fx shard,
/root/reference/oobleck/module/model.py:21-33, sharding.py:15-18), designed
TPU-first: pure-functional params pytrees, bf16 compute / f32 params, static
shapes, and explicit Megatron-style tensor parallelism + fsdp parameter
gathering for full-manual shard_map execution.

Pipeline layer list: [embed, block_0 .. block_{L-1}, head] — L+2 planning
units, matching the reference's "one split point per transformer block + final
norm/head" granularity (sharding.py:15-18).

Parameter layout is chosen for manual TP:
  wqkv [E, 3, H, D]   — heads on a dedicated dim, sharded over `tensor`
  wo   [H, D, E]      — row-parallel output proj
  wi   [E, F] / wo [F, E] — column/row-parallel MLP
  wte  [Vp, E]        — vocab-parallel embedding (Vp = vocab padded to 128)
Every apply function takes an optional ShardCtx; with ctx=None the same code
runs as a plain single-device program (used by tests and the profiler).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from oobleck_tpu.models.base import stack_layer_params
from oobleck_tpu.ops.attention import causal_attention
from oobleck_tpu.parallel.collectives import (
    megatron_f,
    reduce_from_tp,
    unshard_fsdp,
    vocab_parallel_embed,
    vocab_parallel_logits_loss,
)

NEG_INF = -1e9


@dataclass(frozen=True)
class ShardCtx:
    """Axis names for manual-collective execution; None member = skip."""

    tensor: str | None = None
    fsdp: str | None = None
    seq: str | None = None   # sequence parallelism: ring attention + offsets
    # Explicit-backward mode (parallel/overlap.py): value_and_grad runs INSIDE
    # one check_rep=False shard_map, so no spec transposes insert backward
    # psums — the model must place Megatron `f` at each replicated->column-
    # parallel entry and make every forward tensor-psum identity-backward.
    explicit_bwd: bool = False

    def tp_size(self) -> int:
        return lax.axis_size(self.tensor) if self.tensor else 1

    def tp_rank(self):
        return lax.axis_index(self.tensor) if self.tensor else 0

    def seq_rank(self):
        return lax.axis_index(self.seq) if self.seq else 0


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    max_position_embeddings: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int | None = None
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16      # compute/activation dtype
    param_dtype: Any = jnp.float32  # parameter storage dtype
    attention_impl: str = "auto"
    remat: bool = True
    vocab_pad_multiple: int = 128   # pad vocab so `tensor` can shard it
    # "learned" (GPT-2) or "alibi" (Bloom family: no wpe, per-head distance
    # bias in attention).
    position_embedding: str = "learned"

    def __post_init__(self):
        if self.position_embedding not in ("learned", "alibi"):
            raise ValueError(
                f"position_embedding must be 'learned' or 'alibi', got "
                f"{self.position_embedding!r}"
            )

    @property
    def padded_vocab_size(self) -> int:
        m = self.vocab_pad_multiple
        return (self.vocab_size + m - 1) // m * m

    @property
    def ffn_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads

    def override(self, **kwargs) -> "GPTConfig":
        # HF model_args names accepted for config-compat with the reference
        # contract (training_util.py:27-32): n_embd/n_layer/n_head/n_positions.
        alias = {
            "n_embd": "hidden_size",
            "n_layer": "num_layers",
            "n_head": "num_heads",
            "n_positions": "max_position_embeddings",
            "n_inner": "intermediate_size",
        }
        kwargs = {alias.get(k, k): v for k, v in kwargs.items()}
        unknown = [k for k in kwargs if k not in GPTConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(
                f"unknown model_args {unknown}; known fields: "
                f"{sorted(GPTConfig.__dataclass_fields__)} (+ HF aliases {sorted(alias)})"
            )
        return replace(self, **kwargs)


def _layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(dtype)


def _maybe_reduce_from_tp(x, axis, identity_bwd=False):
    return reduce_from_tp(x, axis, identity_bwd=identity_bwd) if axis else x


def _maybe_megatron_f(x, ctx: "ShardCtx | None"):
    """Megatron `f` at a replicated->column-parallel entry, only in
    explicit-backward mode (the default path's spec transposes handle it)."""
    if ctx is not None and ctx.explicit_bwd and ctx.tensor:
        return megatron_f(x, ctx.tensor)
    return x


def _explicit_bwd(ctx: "ShardCtx | None") -> bool:
    return ctx is not None and ctx.explicit_bwd


def _maybe_unshard(p, axis, dim):
    return unshard_fsdp(p, axis, dim) if axis else p


class GPTModel:
    """Layer-list GPT decoder. See module docstring for the pipeline layout."""

    data_kind = "causal_lm"
    fused_supported = True  # the compiled SPMD step (parallel/train.py)

    def __init__(self, config: GPTConfig):
        self.config = config

    # ------------------------------------------------------------------ #
    # layer list view (planning / MPMD pipeline)                          #
    # ------------------------------------------------------------------ #

    @property
    def num_pipeline_layers(self) -> int:
        return self.config.num_layers + 2

    def layer_name(self, index: int) -> str:
        if index == 0:
            return "embed"
        if index == self.num_pipeline_layers - 1:
            return "head"
        return f"block_{index - 1}"

    def init_layer(self, rng: jax.Array, index: int):
        # Same key derivation as init_params so the layer-list and fused
        # views of one seed produce identical weights.
        ks = jax.random.split(rng, 3)
        if index == 0:
            return self._init_embed(ks[0])
        if index == self.num_pipeline_layers - 1:
            return self._init_head(ks[2])
        return self._init_block(jax.random.fold_in(ks[1], index))

    def apply_layer(self, index: int, params, carry, batch, ctx: ShardCtx | None = None):
        if index == 0:
            return self.embed(params, batch["input_ids"], ctx)
        if index == self.num_pipeline_layers - 1:
            return self.head(params, carry, ctx)
        return self.apply_block(params, carry, ctx)

    def loss_from_logits(self, logits: jax.Array, batch) -> jax.Array:
        return cross_entropy_loss(logits, batch["input_ids"], self.config.vocab_size)

    def sample_batch(self, batch_size: int, seq_len: int):
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (batch_size, seq_len), 0, self.config.vocab_size,
            dtype=jnp.int32,
        )
        return {"input_ids": tokens}

    # ------------------------------------------------------------------ #
    # parameter init                                                      #
    # ------------------------------------------------------------------ #

    def _init_embed(self, rng: jax.Array):
        c = self.config
        k1, k2 = jax.random.split(rng)
        std = c.initializer_range
        out = {
            "wte": jax.random.normal(k1, (c.padded_vocab_size, c.hidden_size), c.param_dtype) * std,
        }
        if c.position_embedding == "learned":
            out["wpe"] = jax.random.normal(
                k2, (c.max_position_embeddings, c.hidden_size), c.param_dtype
            ) * std
        return out

    def _init_block(self, rng: jax.Array):
        c = self.config
        ks = jax.random.split(rng, 4)
        std = c.initializer_range
        # GPT-2 residual-projection scaling: 1/sqrt(2*L) on the output projs.
        res_std = std / (2 * c.num_layers) ** 0.5
        e, f, h, d = c.hidden_size, c.ffn_dim, c.num_heads, c.head_dim
        return {
            "ln1": {"scale": jnp.ones((e,), c.param_dtype), "bias": jnp.zeros((e,), c.param_dtype)},
            "attn": {
                "wqkv": jax.random.normal(ks[0], (e, 3, h, d), c.param_dtype) * std,
                "bqkv": jnp.zeros((3, h, d), c.param_dtype),
                "wo": jax.random.normal(ks[1], (h, d, e), c.param_dtype) * res_std,
                "bo": jnp.zeros((e,), c.param_dtype),
            },
            "ln2": {"scale": jnp.ones((e,), c.param_dtype), "bias": jnp.zeros((e,), c.param_dtype)},
            "mlp": {
                "wi": jax.random.normal(ks[2], (e, f), c.param_dtype) * std,
                "bi": jnp.zeros((f,), c.param_dtype),
                "wo": jax.random.normal(ks[3], (f, e), c.param_dtype) * res_std,
                "bo": jnp.zeros((e,), c.param_dtype),
            },
        }

    def _init_head(self, rng: jax.Array):
        c = self.config
        e = c.hidden_size
        return {
            "ln_f": {"scale": jnp.ones((e,), c.param_dtype), "bias": jnp.zeros((e,), c.param_dtype)},
            # Untied lm head, matching the reference's behavior of not tying
            # embeddings across first/last stages (README.md:99).
            "w": jax.random.normal(rng, (e, c.padded_vocab_size), c.param_dtype) * c.initializer_range,
        }

    def init_params(self, rng: jax.Array):
        """Fused view: blocks stacked on a leading [num_layers, ...] axis."""
        ks = jax.random.split(rng, 3)
        blocks = [self._init_block(jax.random.fold_in(ks[1], i + 1))
                  for i in range(self.config.num_layers)]
        return {
            "embed": self._init_embed(ks[0]),
            "blocks": stack_layer_params(blocks),
            "head": self._init_head(ks[2]),
        }

    # ------------------------------------------------------------------ #
    # forward (ctx=None: plain; ctx set: manual TP/fsdp collectives)      #
    # ------------------------------------------------------------------ #

    def embed(self, p, tokens: jax.Array, ctx: ShardCtx | None = None) -> jax.Array:
        c = self.config
        seq = tokens.shape[-1]
        if ctx and ctx.tensor:
            vlocal = p["wte"].shape[0]
            offset = ctx.tp_rank() * vlocal
            x = vocab_parallel_embed(p["wte"], tokens, offset, ctx.tensor,
                                     identity_bwd=_explicit_bwd(ctx))
        else:
            x = p["wte"][tokens]
        if c.position_embedding == "learned":
            if ctx and ctx.seq:
                # Sequence-parallel: this shard holds [r*seq, (r+1)*seq).
                pos0 = ctx.seq_rank() * seq
                x = x + lax.dynamic_slice_in_dim(p["wpe"], pos0, seq, axis=0)
            else:
                x = x + p["wpe"][:seq]
        return x.astype(c.dtype)

    def apply_block(self, p, x: jax.Array, ctx: ShardCtx | None = None) -> jax.Array:
        x = self.attention_sublayer(p, x, ctx)
        return self.mlp_sublayer(p, x, ctx)

    def mlp_sublayer(self, p, x: jax.Array, ctx: ShardCtx | None = None) -> jax.Array:
        """ln2 -> gelu MLP -> residual. Shape-agnostic over leading dims:
        the decode path calls it on [B, E] single-token activations."""
        c = self.config
        dt = c.dtype
        t = ctx.tensor if ctx else None
        f_ = ctx.fsdp if ctx else None
        h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"], c.layer_norm_epsilon)
        h = _maybe_megatron_f(h, ctx)
        wi = _maybe_unshard(p["mlp"]["wi"], f_, 0).astype(dt)           # [E,Fl]
        h = jax.nn.gelu(h @ wi + p["mlp"]["bi"].astype(dt))
        wo = _maybe_unshard(p["mlp"]["wo"], f_, 1).astype(dt)           # [Fl,E]
        out = h @ wo
        out = _maybe_reduce_from_tp(out, t, _explicit_bwd(ctx)) + p["mlp"]["bo"].astype(dt)
        return x + out

    def attention_sublayer(self, p, x: jax.Array,
                           ctx: ShardCtx | None = None, *,
                           return_kv: bool = False):
        """ln1 -> attention (impl dispatch, ALiBi, TP/SP aware) -> residual.
        Split out of apply_block so MoE variants swap only the MLP half.
        `return_kv=True` (prefill) also returns this layer's K/V [B, H, S, D]
        for the serving KV cache."""
        c = self.config
        dt = c.dtype
        t = ctx.tensor if ctx else None
        f_ = ctx.fsdp if ctx else None

        # --- attention ---
        # (Megatron `f` only in explicit_bwd mode: on the default path the
        # shard_map spec transpose psums the replicated->varying boundary
        # cotangent itself; see the regime note in collectives.py.)
        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], c.layer_norm_epsilon)
        h = _maybe_megatron_f(h, ctx)
        wqkv = _maybe_unshard(p["attn"]["wqkv"], f_, 0).astype(dt)     # [E,3,Hl,D]
        bqkv = p["attn"]["bqkv"].astype(dt)                             # [3,Hl,D]
        qkv = jnp.einsum("bse,ethd->tbhsd", h, wqkv) + bqkv[:, None, :, None, :]

        def local_alibi_slopes():
            # Slopes only ([Hl] after the TP-local slice) — never a
            # materialized [H, S, S] bias: the flash kernel generates the
            # bias IN-KERNEL (zero HBM bias bytes at any S); non-flash
            # fallbacks and the Ulysses seq-shard materialize only their
            # own head block from these slopes (round-4 advisor: the
            # full bias was O(H S^2) HBM per device).
            if c.position_embedding != "alibi":
                return None
            from oobleck_tpu.ops.attention import alibi_slopes

            full = alibi_slopes(c.num_heads)
            if ctx and ctx.tensor:
                h_local = qkv.shape[2]
                return lax.dynamic_slice_in_dim(
                    full, ctx.tp_rank() * h_local, h_local, axis=0)
            return full

        if ctx and ctx.seq:
            if c.attention_impl == "ulysses" or c.position_embedding == "alibi":
                # Ulysses all-to-all layout: full sequence per device on
                # H/P heads — position-dependent biases (ALiBi) work
                # unchanged, which the ring layout cannot offer.
                from oobleck_tpu.ops.ulysses import ulysses_attention

                attn_out = ulysses_attention(
                    qkv[0], qkv[1], qkv[2], axis_name=ctx.seq,
                    alibi_slopes=local_alibi_slopes(),
                )
            else:
                from oobleck_tpu.ops.ring_attention import ring_attention

                attn_out = ring_attention(qkv[0], qkv[1], qkv[2],
                                          axis_name=ctx.seq)
        else:
            attn_out = causal_attention(
                qkv[0], qkv[1], qkv[2], impl=c.attention_impl,
                alibi_slopes=local_alibi_slopes(),
                constant_bias=True,  # ALiBi is position-only
            )
        wo = _maybe_unshard(p["attn"]["wo"], f_, 2).astype(dt)          # [Hl,D,E]
        out = jnp.einsum("bhsd,hde->bse", attn_out, wo)
        out = _maybe_reduce_from_tp(out, t, _explicit_bwd(ctx)) + p["attn"]["bo"].astype(dt)
        if return_kv:
            return x + out, qkv[1], qkv[2]
        return x + out

    def head(self, p, x: jax.Array, ctx: ShardCtx | None = None) -> jax.Array:
        """Full (unsharded-output) logits in f32; masks vocab padding."""
        c = self.config
        x = _layer_norm(x, p["ln_f"]["scale"], p["ln_f"]["bias"], c.layer_norm_epsilon)
        logits = (x @ p["w"].astype(c.dtype)).astype(jnp.float32)
        if ctx and ctx.tensor:
            logits = lax.all_gather(logits, ctx.tensor, axis=-1, tiled=True)
        mask = jnp.arange(logits.shape[-1]) < c.vocab_size
        return jnp.where(mask, logits, NEG_INF)

    def head_loss_shifted(self, p, x: jax.Array, targets: jax.Array,
                          mask: jax.Array, ctx: ShardCtx | None = None) -> jax.Array:
        """SUM of masked per-position losses with *pre-shifted* targets
        (targets[t] = token[t+1], mask 0 on invalid positions).

        Used by the sequence-parallel fused path: the next-token shift
        crosses shard boundaries when the sequence dim is sharded, so the
        caller shifts globally before sharding instead."""
        c = self.config
        x = _layer_norm(x, p["ln_f"]["scale"], p["ln_f"]["bias"], c.layer_norm_epsilon)
        x = _maybe_megatron_f(x, ctx)
        local_logits = (x @ p["w"].astype(c.dtype)).astype(jnp.float32)
        vlocal = local_logits.shape[-1]
        offset = (ctx.tp_rank() * vlocal) if (ctx and ctx.tensor) else 0
        col_ids = jnp.arange(vlocal) + offset
        local_logits = jnp.where(col_ids < c.vocab_size, local_logits, NEG_INF)
        per_pos = vocab_parallel_logits_loss(
            local_logits, targets, offset, ctx.tensor if ctx else None,
            identity_bwd=_explicit_bwd(ctx),
        )
        return jnp.sum(per_pos * mask)

    def forward(self, params, tokens: jax.Array) -> jax.Array:
        """Fused single-program forward over stacked blocks (ctx-free)."""
        c = self.config
        x = self.embed(params["embed"], tokens)
        block = self.apply_block
        if c.remat:
            block = jax.checkpoint(block)

        def body(x, bp):
            return block(bp, x), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
        return self.head(params["head"], x)

    def loss(self, params, batch) -> jax.Array:
        return self.loss_from_logits(self.forward(params, batch["input_ids"]), batch)

    # ------------------------------------------------------------------ #
    # incremental decode (serving)                                        #
    # ------------------------------------------------------------------ #

    def init_kv_cache(self, batch_size: int, max_seq: int, dtype: Any = None):
        """Preallocated per-layer KV cache, stacked [L, B, H, S, D] (compute
        dtype, bf16 by default) so decode scans blocks and cache slices
        together. `batch_size` is the number of continuous-batching slots."""
        c = self.config
        shape = (c.num_layers, batch_size, c.num_heads, max_seq, c.head_dim)
        dt = c.dtype if dtype is None else dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def _decode_attention_sublayer(self, p, x, k_cache, v_cache, pos):
        """attention_sublayer for ONE new token per slot against the KV
        cache. x [B, E]; k_cache/v_cache [B, H, S, D]; pos [B]."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.attention import (
            alibi_slopes, cache_write, decode_attention)

        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], c.layer_norm_epsilon)
        wqkv = p["attn"]["wqkv"].astype(dt)                             # [E,3,H,D]
        qkv = jnp.einsum("be,ethd->tbhd", h, wqkv) + p["attn"]["bqkv"].astype(dt)[:, None]
        k_cache = cache_write(k_cache, qkv[1], pos)
        v_cache = cache_write(v_cache, qkv[2], pos)
        slopes = alibi_slopes(c.num_heads) if c.position_embedding == "alibi" else None
        attn = decode_attention(qkv[0], k_cache, v_cache, pos, alibi_slopes=slopes)
        out = jnp.einsum("bhd,hde->be", attn, p["attn"]["wo"].astype(dt))
        out = out + p["attn"]["bo"].astype(dt)
        return x + out, k_cache, v_cache

    def forward_prefill(self, params, tokens: jax.Array, kv_cache,
                        slot: jax.Array, length: jax.Array):
        """Prompt pass for ONE request: training-mode block math over
        tokens [1, T] (T may be padded past the live `length`), writing each
        layer's K/V into batch slot `slot` of the cache. Returns (next-token
        logits [V] f32 taken at position length-1, updated cache). Padded
        positions land in the cache but are never attended: prefill is
        causal and decode masks k_idx <= pos, and every decode step
        overwrites its own position before reading it."""
        x = self.embed(params["embed"], tokens)

        def body(x, bp):
            x, k, v = self.attention_sublayer(bp, x, return_kv=True)
            return self.mlp_sublayer(bp, x), (k, v)

        x, (ks, vs) = lax.scan(body, x, params["blocks"])
        # ks/vs [L, 1, H, T, D]: one slice-write into slot `slot`.
        k_cache = lax.dynamic_update_slice(
            kv_cache["k"], ks.astype(kv_cache["k"].dtype), (0, slot, 0, 0, 0))
        v_cache = lax.dynamic_update_slice(
            kv_cache["v"], vs.astype(kv_cache["v"].dtype), (0, slot, 0, 0, 0))
        logits = self.head(params["head"], x)[0, length - 1]
        return logits, {"k": k_cache, "v": v_cache}

    def forward_decode(self, params, token: jax.Array, kv_cache, pos: jax.Array):
        """One decode step for a batch of slots: token [B] (each slot's
        previous token), pos [B] (its position), cache from init_kv_cache.
        Returns (logits [B, V] f32, updated cache). Inactive slots decode
        garbage harmlessly — their slot is rewritten by the next prefill."""
        c = self.config
        pe = params["embed"]
        x = pe["wte"][token]
        if c.position_embedding == "learned":
            x = x + pe["wpe"][pos]
        x = x.astype(c.dtype)

        def body(x, sl):
            bp, kc, vc = sl
            x, kc, vc = self._decode_attention_sublayer(bp, x, kc, vc, pos)
            return self.mlp_sublayer(bp, x), (kc, vc)

        x, (k_new, v_new) = lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        logits = self.head(params["head"], x[:, None, :])[:, 0]
        return logits, {"k": k_new, "v": v_new}

    # ------------------------------------------------------------------ #
    # paged incremental decode (serving, block-table KV)                  #
    # ------------------------------------------------------------------ #

    def init_paged_kv_cache(self, num_pages: int, page_size: int,
                            dtype: Any = None):
        """Paged KV pool, stacked [L, N_pages, H, page, D]. Requests own
        page chains via block tables (serve/kv_blocks.py); page 0 is the
        reserved garbage page inactive lanes and padding write to."""
        c = self.config
        shape = (c.num_layers, num_pages, c.num_heads, page_size, c.head_dim)
        dt = c.dtype if dtype is None else dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def _paged_impl(self) -> str:
        # Training impl names (ring/ulysses) have no paged meaning; only a
        # forced xla/pallas carries over, everything else resolves by
        # backend.
        impl = self.config.attention_impl
        return impl if impl in ("xla", "pallas") else "auto"

    def _paged_decode_sublayer(self, p, x, k_pool, v_pool, block_tables, pos):
        """_decode_attention_sublayer against a page pool: write the new
        token's K/V through the block table, then ragged paged attention.
        x [B, E]; pools [N, H, page, D]; block_tables [B, P]; pos [B]."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.attention import alibi_slopes
        from oobleck_tpu.ops.paged_attention import (
            paged_cache_write, paged_decode_attention)

        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], c.layer_norm_epsilon)
        wqkv = p["attn"]["wqkv"].astype(dt)                             # [E,3,H,D]
        qkv = jnp.einsum("be,ethd->tbhd", h, wqkv) + p["attn"]["bqkv"].astype(dt)[:, None]
        k_pool = paged_cache_write(k_pool, qkv[1], block_tables, pos)
        v_pool = paged_cache_write(v_pool, qkv[2], block_tables, pos)
        slopes = alibi_slopes(c.num_heads) if c.position_embedding == "alibi" else None
        attn = paged_decode_attention(
            qkv[0], k_pool, v_pool, block_tables, pos + 1,
            alibi_slopes=slopes, impl=self._paged_impl())
        out = jnp.einsum("bhd,hde->be", attn, p["attn"]["wo"].astype(dt))
        out = out + p["attn"]["bo"].astype(dt)
        return x + out, k_pool, v_pool

    def _tail_prefill_sublayer(self, p, x, k_pool, v_pool, head_tables,
                               prior_len):
        """attention_sublayer for a prompt TAIL whose head (`prior_len`
        tokens) is already cached in pool pages named by `head_tables`
        (static page count, garbage-padded past the live head): the prefix
        hit skips the head's block compute entirely — head K/V are
        GATHERED, not recomputed. Tail queries sit at absolute positions
        prior_len + i, so the mask is explicit (head key j live iff
        j < prior_len; causal among the tail) and ALiBi uses true
        distances. seq_q != seq_k, so this is inherently the XLA path."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.attention import (
            _xla_causal_attention, alibi_slopes)
        from oobleck_tpu.ops.paged_attention import paged_gather_kv

        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], c.layer_norm_epsilon)
        wqkv = p["attn"]["wqkv"].astype(dt)
        qkv = jnp.einsum("bse,ethd->tbhsd", h, wqkv) + p["attn"]["bqkv"].astype(dt)[:, None, :, None, :]
        q, k_tail, v_tail = qkv[0], qkv[1], qkv[2]
        head_k = paged_gather_kv(k_pool, head_tables[None]).astype(dt)
        head_v = paged_gather_kv(v_pool, head_tables[None]).astype(dt)
        k = jnp.concatenate([head_k, k_tail], axis=2)
        v = jnp.concatenate([head_v, v_tail], axis=2)
        t_len, s_head = q.shape[2], head_k.shape[2]
        q_abs = prior_len + jnp.arange(t_len)                           # [T]
        k_abs = jnp.concatenate([jnp.arange(s_head), q_abs])            # [S]
        live = jnp.concatenate([
            jnp.broadcast_to(jnp.arange(s_head) < prior_len, (t_len, s_head)),
            jnp.tril(jnp.ones((t_len, t_len), bool)),
        ], axis=1)                                                      # [T, S]
        bias = jnp.where(live, 0.0, NEG_INF)[None]                      # [1,T,S]
        if c.position_embedding == "alibi":
            dist = (q_abs[:, None] - k_abs[None, :]).astype(jnp.float32)
            bias = bias - alibi_slopes(c.num_heads)[:, None, None] * dist
        attn = _xla_causal_attention(q, k, v, bias=bias, causal=False)
        out = jnp.einsum("bhsd,hde->bse", attn, p["attn"]["wo"].astype(dt))
        out = out + p["attn"]["bo"].astype(dt)
        return x + out, k_tail, v_tail

    def _paged_tail_write(self, kv_cache, ks, vs, block_tables, prior_len,
                          length):
        """Scatter a prefill tail's K/V ([L, 1, Hkv, T, D]) into pool pages
        at absolute positions prior_len + i. Padded positions (i >= length)
        land on the garbage page 0."""
        page = kv_cache["k"].shape[3]
        t_len = ks.shape[3]
        i = jnp.arange(t_len)
        pos_abs = prior_len + i
        page_idx = jnp.where(
            i < length,
            jnp.take(block_tables, pos_abs // page, mode="clip"), 0)    # [T]
        off = pos_abs % page
        # Advanced indices at dims 1/3 front the result: update [T, L, H, D].
        upd_k = ks[:, 0].transpose(2, 0, 1, 3).astype(kv_cache["k"].dtype)
        upd_v = vs[:, 0].transpose(2, 0, 1, 3).astype(kv_cache["v"].dtype)
        return {
            "k": kv_cache["k"].at[:, page_idx, :, off, :].set(upd_k),
            "v": kv_cache["v"].at[:, page_idx, :, off, :].set(upd_v),
        }

    def forward_prefill_paged(self, params, tokens: jax.Array, kv_cache,
                              block_tables: jax.Array, length: jax.Array,
                              head_tables: jax.Array | None = None,
                              prior_len: jax.Array | int = 0):
        """Prompt pass for ONE request into pool pages. tokens [1, T] is the
        prompt TAIL (bucket-padded past the live `length`); block_tables [P]
        names the request's page chain (cached head included); on a prefix
        hit `head_tables` [P_head] (static count — a jit bucket) names the
        cached head pages and `prior_len` its live token count, and the
        head's compute is skipped. Returns (next-token logits [V] f32 at
        tail position length-1, updated pool)."""
        c = self.config
        t_len = tokens.shape[-1]
        prior_len = jnp.asarray(prior_len, jnp.int32)
        pe = params["embed"]
        x = pe["wte"][tokens]
        if c.position_embedding == "learned":
            x = x + lax.dynamic_slice_in_dim(pe["wpe"], prior_len, t_len, axis=0)
        x = x.astype(c.dtype)

        def body(x, sl):
            bp, kp, vp = sl
            if head_tables is None:
                x, k, v = self.attention_sublayer(bp, x, return_kv=True)
            else:
                x, k, v = self._tail_prefill_sublayer(
                    bp, x, kp, vp, head_tables, prior_len)
            return self.mlp_sublayer(bp, x), (k, v)

        x, (ks, vs) = lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        kv_cache = self._paged_tail_write(
            kv_cache, ks, vs, block_tables, prior_len, length)
        logits = self.head(params["head"], x)[0, length - 1]
        return logits, kv_cache

    def forward_decode_paged(self, params, token: jax.Array, kv_cache,
                             block_tables: jax.Array, pos: jax.Array):
        """One paged decode step over all lanes: token [B], pos [B],
        block_tables [B, P]. Same contract as forward_decode; inactive
        lanes park on the garbage page and decode harmlessly."""
        c = self.config
        pe = params["embed"]
        x = pe["wte"][token]
        if c.position_embedding == "learned":
            x = x + pe["wpe"][pos]
        x = x.astype(c.dtype)

        def body(x, sl):
            bp, kp, vp = sl
            x, kp, vp = self._paged_decode_sublayer(
                bp, x, kp, vp, block_tables, pos)
            return self.mlp_sublayer(bp, x), (kp, vp)

        x, (k_new, v_new) = lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        logits = self.head(params["head"], x[:, None, :])[:, 0]
        return logits, {"k": k_new, "v": v_new}

    def _paged_verify_sublayer(self, p, x, k_pool, v_pool, block_tables,
                               pos, n_live):
        """_paged_decode_sublayer for T speculative tokens per lane: write
        all T candidates' K/V through the block table (padding past
        n_live lands on the garbage page), then ragged multi-query
        attention where row i attends keys < pos + 1 + i. x [B, T, E];
        pools [N, H, page, D]; block_tables [B, P]; pos/n_live [B]."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.attention import alibi_slopes
        from oobleck_tpu.ops.paged_attention import (
            paged_cache_write_multi, paged_verify_attention)

        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], c.layer_norm_epsilon)
        wqkv = p["attn"]["wqkv"].astype(dt)                             # [E,3,H,D]
        qkv = jnp.einsum("bse,ethd->tbshd", h, wqkv) \
            + p["attn"]["bqkv"].astype(dt)[:, None, None]               # [3,B,T,H,D]
        k_pool = paged_cache_write_multi(
            k_pool, qkv[1], block_tables, pos, n_live)
        v_pool = paged_cache_write_multi(
            v_pool, qkv[2], block_tables, pos, n_live)
        slopes = alibi_slopes(c.num_heads) if c.position_embedding == "alibi" else None
        attn = paged_verify_attention(
            qkv[0], k_pool, v_pool, block_tables, pos + 1,
            alibi_slopes=slopes, impl=self._paged_impl())
        out = jnp.einsum("bthd,hde->bte", attn, p["attn"]["wo"].astype(dt))
        out = out + p["attn"]["bo"].astype(dt)
        return x + out, k_pool, v_pool

    def forward_verify_paged(self, params, tokens: jax.Array, kv_cache,
                             block_tables: jax.Array, pos: jax.Array,
                             n_live: jax.Array):
        """One speculative verify step over all lanes: tokens [B, T] (lane
        b's last emitted token followed by its k = T-1 draft candidates;
        columns past n_live[b] are bucket padding), pos [B] (absolute
        position of column 0), block_tables [B, P]. Column i embeds and
        attends at absolute position pos + i (wpe / ALiBi true distance),
        and its K/V is written through the table exactly as a sequential
        decode would have. Returns (logits [B, T, V] f32, updated pool);
        row i scores the token for position pos + i + 1, so row 0 of a
        T=1 call reproduces forward_decode_paged. Padded columns write to
        the garbage page and score garbage harmlessly."""
        c = self.config
        t_len = tokens.shape[-1]
        pe = params["embed"]
        x = pe["wte"][tokens]                                           # [B,T,E]
        if c.position_embedding == "learned":
            # Clip: a padded column of a near-max_seq lane may index past
            # the table; its output is garbage (and masked) either way.
            pos_abs = jnp.clip(
                pos[:, None] + jnp.arange(t_len), 0, pe["wpe"].shape[0] - 1)
            x = x + pe["wpe"][pos_abs]
        x = x.astype(c.dtype)

        def body(x, sl):
            bp, kp, vp = sl
            x, kp, vp = self._paged_verify_sublayer(
                bp, x, kp, vp, block_tables, pos, n_live)
            return self.mlp_sublayer(bp, x), (kp, vp)

        x, (k_new, v_new) = lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        logits = self.head(params["head"], x)
        return logits, {"k": k_new, "v": v_new}

    # ------------------------------------------------------------------ #
    # sharding + gradient-reduction rules                                 #
    # ------------------------------------------------------------------ #

    def param_specs(self, *, stacked: bool = True):
        """PartitionSpecs for full-manual execution over mesh axes
        (data, stage, fsdp, tensor). Blocks carry a leading layer dim sharded
        over `stage` when stacked."""
        s = ("stage",) if stacked else ()

        block = {
            "ln1": {"scale": P(*s), "bias": P(*s)},
            "attn": {
                "wqkv": P(*s, "fsdp", None, "tensor", None),
                "bqkv": P(*s, None, "tensor", None),
                "wo": P(*s, "tensor", None, "fsdp"),
                "bo": P(*s),
            },
            "ln2": {"scale": P(*s), "bias": P(*s)},
            "mlp": {
                "wi": P(*s, "fsdp", "tensor"),
                "bi": P(*s, "tensor"),
                "wo": P(*s, "tensor", "fsdp"),
                "bo": P(*s),
            },
        }
        embed = {"wte": P("tensor", None)}
        if self.config.position_embedding == "learned":
            embed["wpe"] = P(None, None)
        head = {"ln_f": {"scale": P(), "bias": P()}, "w": P(None, "tensor")}
        return {"embed": embed, "blocks": block, "head": head}


def cross_entropy_loss(logits: jax.Array, tokens: jax.Array,
                       vocab_size: int | None = None) -> jax.Array:
    """Next-token LM loss: positions :-1 predict tokens 1:. Any leading dims.
    `vocab_size` masks padded vocab columns when logits are padded."""
    logits = logits[..., :-1, :].astype(jnp.float32)
    if vocab_size is not None and logits.shape[-1] > vocab_size:
        mask = jnp.arange(logits.shape[-1]) < vocab_size
        logits = jnp.where(mask, logits, NEG_INF)
    targets = tokens[..., 1:]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)
