"""Llama family decoder as an explicit layer list.

BASELINE.json config 5 ("Llama-2-7B via HF model_name — stretch the template
planner to non-GPT arch"); the reference cannot run Llama at all (its split
registry has no llama entry, /root/reference/oobleck/module/sharding.py:15-41).

Same pipeline layer list contract as GPT ([embed, block_0.., head], see
models/gpt.py) and the same ShardCtx manual-parallel protocol, with the Llama
architecture: RMSNorm, rotary position embeddings (no learned positions —
seq-parallel offsets rotate RoPE phases instead of slicing a table), SwiGLU
MLP, no biases, untied head, optional grouped-query attention.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from oobleck_tpu.models.base import stack_layer_params
from oobleck_tpu.models.gpt import (
    NEG_INF,
    ShardCtx,
    _explicit_bwd,
    _maybe_megatron_f,
)
from oobleck_tpu.ops.attention import causal_attention
from oobleck_tpu.parallel.collectives import (
    reduce_from_tp,
    unshard_fsdp,
    vocab_parallel_embed,
    vocab_parallel_logits_loss,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_position_embeddings: int = 4096
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int | None = None     # None = MHA
    intermediate_size: int | None = None
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"
    remat: bool = True
    vocab_pad_multiple: int = 128

    @property
    def padded_vocab_size(self) -> int:
        m = self.vocab_pad_multiple
        return (self.vocab_size + m - 1) // m * m

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def ffn_dim(self) -> int:
        if self.intermediate_size:
            return self.intermediate_size
        # Llama sizing: 2/3 * 4E rounded up to a multiple of 256.
        f = int(2 * 4 * self.hidden_size / 3)
        return (f + 255) // 256 * 256

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads

    def override(self, **kwargs) -> "LlamaConfig":
        alias = {
            "n_embd": "hidden_size", "n_layer": "num_layers",
            "n_head": "num_heads", "n_positions": "max_position_embeddings",
        }
        kwargs = {alias.get(k, k): v for k, v in kwargs.items()}
        unknown = [k for k in kwargs if k not in LlamaConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        return replace(self, **kwargs)


def _rms_norm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale).astype(dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [B, H, S, D]; positions: [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


def _rope_one(x: jax.Array, pos: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding for one token per batch row (decode step).
    x: [B, H, D]; pos: [B] — the same phases `_rope` applies at these
    absolute positions, so cache entries and decode queries agree."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[:, None].astype(jnp.float32) * freqs[None, :]  # [B, D/2]
    cos, sin = jnp.cos(angles)[:, None, :], jnp.sin(angles)[:, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


def _rope_multi(x: jax.Array, pos: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding for T tokens per batch row at per-lane absolute
    positions (speculative verify). x: [B, H, T, D]; pos: [B, T] — the
    same phases `_rope`/`_rope_one` apply at these positions, so cached
    keys and verify queries agree with a sequential decode."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[..., None].astype(jnp.float32) * freqs      # [B, T, D/2]
    cos, sin = jnp.cos(angles)[:, None], jnp.sin(angles)[:, None]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.stack([out1, out2], axis=-1).reshape(x.shape).astype(x.dtype)


def _maybe(fn, x, axis, *a):
    return fn(x, axis, *a) if axis else x


def _maybe_reduce(x, axis, ctx):
    return reduce_from_tp(x, axis, identity_bwd=_explicit_bwd(ctx)) if axis else x


class LlamaModel:
    """Layer-list Llama decoder; same contract as GPTModel."""

    data_kind = "causal_lm"
    fused_supported = True

    def __init__(self, config: LlamaConfig):
        self.config = config

    # ---- layer list ----

    @property
    def num_pipeline_layers(self) -> int:
        return self.config.num_layers + 2

    def layer_name(self, index: int) -> str:
        if index == 0:
            return "embed"
        if index == self.num_pipeline_layers - 1:
            return "head"
        return f"block_{index - 1}"

    def init_layer(self, rng: jax.Array, index: int):
        ks = jax.random.split(rng, 3)
        if index == 0:
            return self._init_embed(ks[0])
        if index == self.num_pipeline_layers - 1:
            return self._init_head(ks[2])
        return self._init_block(jax.random.fold_in(ks[1], index))

    def apply_layer(self, index: int, params, carry, batch, ctx=None):
        if index == 0:
            return self.embed(params, batch["input_ids"], ctx)
        if index == self.num_pipeline_layers - 1:
            return self.head(params, carry, ctx)
        return self.apply_block(params, carry, ctx)

    def loss_from_logits(self, logits, batch):
        from oobleck_tpu.models.gpt import cross_entropy_loss

        return cross_entropy_loss(logits, batch["input_ids"], self.config.vocab_size)

    def sample_batch(self, batch_size: int, seq_len: int):
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (batch_size, seq_len), 0,
            self.config.vocab_size, dtype=jnp.int32,
        )
        return {"input_ids": tokens}

    # ---- init ----

    def _init_embed(self, rng):
        c = self.config
        return {"wte": jax.random.normal(
            rng, (c.padded_vocab_size, c.hidden_size), c.param_dtype
        ) * c.initializer_range}

    def _init_block(self, rng):
        c = self.config
        ks = jax.random.split(rng, 5)
        std = c.initializer_range
        res_std = std / (2 * c.num_layers) ** 0.5
        e, f, h, kv, d = (c.hidden_size, c.ffn_dim, c.num_heads,
                          c.kv_heads, c.head_dim)
        return {
            "ln1": {"scale": jnp.ones((e,), c.param_dtype)},
            "attn": {
                "wq": jax.random.normal(ks[0], (e, h, d), c.param_dtype) * std,
                "wkv": jax.random.normal(ks[1], (e, 2, kv, d), c.param_dtype) * std,
                "wo": jax.random.normal(ks[2], (h, d, e), c.param_dtype) * res_std,
            },
            "ln2": {"scale": jnp.ones((e,), c.param_dtype)},
            "mlp": {
                "wg": jax.random.normal(ks[3], (e, f), c.param_dtype) * std,
                "wu": jax.random.normal(ks[4], (e, f), c.param_dtype) * std,
                "wo": jax.random.normal(
                    jax.random.fold_in(ks[3], 1), (f, e), c.param_dtype
                ) * res_std,
            },
        }

    def _init_head(self, rng):
        c = self.config
        return {
            "ln_f": {"scale": jnp.ones((c.hidden_size,), c.param_dtype)},
            "w": jax.random.normal(
                rng, (c.hidden_size, c.padded_vocab_size), c.param_dtype
            ) * c.initializer_range,
        }

    def init_params(self, rng):
        ks = jax.random.split(rng, 3)
        blocks = [self._init_block(jax.random.fold_in(ks[1], i + 1))
                  for i in range(self.config.num_layers)]
        return {"embed": self._init_embed(ks[0]),
                "blocks": stack_layer_params(blocks),
                "head": self._init_head(ks[2])}

    # ---- forward ----

    def embed(self, p, tokens, ctx: ShardCtx | None = None):
        c = self.config
        if ctx and ctx.tensor:
            vlocal = p["wte"].shape[0]
            x = vocab_parallel_embed(p["wte"], tokens,
                                     ctx.tp_rank() * vlocal, ctx.tensor,
                                     identity_bwd=_explicit_bwd(ctx))
        else:
            x = p["wte"][tokens]
        return x.astype(c.dtype)

    def _positions(self, s_local: int, ctx: ShardCtx | None):
        if ctx and ctx.seq:
            return ctx.seq_rank() * s_local + jnp.arange(s_local)
        return jnp.arange(s_local)

    def apply_block(self, p, x, ctx: ShardCtx | None = None):
        x = self.attention_sublayer(p, x, ctx)
        return self.mlp_sublayer(p, x, ctx)

    def attention_sublayer(self, p, x, ctx: ShardCtx | None = None, *,
                           return_kv: bool = False):
        """ln1 -> RoPE attention (GQA, SP aware) -> residual. `return_kv=True`
        (prefill) also returns this layer's post-RoPE, pre-repeat K/V
        [B, KV, S, D] — the form the serving cache stores."""
        c = self.config
        dt = c.dtype
        t = ctx.tensor if ctx else None
        f_ = ctx.fsdp if ctx else None
        b, s, _ = x.shape
        pos = self._positions(s, ctx)

        # (Megatron `f` only in explicit_bwd mode: on the default path the
        # shard_map spec transpose supplies the backward psum at the
        # replicated->varying boundary; see the regime note in collectives.py.)
        h = _rms_norm(x, p["ln1"]["scale"], c.rms_norm_eps)
        h = _maybe_megatron_f(h, ctx)
        wq = _maybe(unshard_fsdp, p["attn"]["wq"], f_, 0).astype(dt)      # [E,Hl,D]
        wkv = _maybe(unshard_fsdp, p["attn"]["wkv"], f_, 0).astype(dt)    # [E,2,KVl,D]
        q = jnp.einsum("bse,ehd->bhsd", h, wq)
        kv = jnp.einsum("bse,ekhd->kbhsd", h, wkv)
        k, v = kv[0], kv[1]
        q = _rope(q, pos, c.rope_theta)
        k = _rope(k, pos, c.rope_theta)
        cached_k, cached_v = k, v
        if c.kv_heads != c.num_heads:
            rep = c.num_heads // c.kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if ctx and ctx.seq:
            from oobleck_tpu.ops.ring_attention import ring_attention

            attn = ring_attention(q, k, v, axis_name=ctx.seq)
        else:
            attn = causal_attention(q, k, v, impl=c.attention_impl)
        wo = _maybe(unshard_fsdp, p["attn"]["wo"], f_, 2).astype(dt)      # [Hl,D,E]
        out = jnp.einsum("bhsd,hde->bse", attn, wo)
        y = x + _maybe_reduce(out, t, ctx)
        if return_kv:
            return y, cached_k, cached_v
        return y

    def mlp_sublayer(self, p, x, ctx: ShardCtx | None = None):
        """ln2 -> SwiGLU -> residual. Shape-agnostic over leading dims: the
        decode path calls it on [B, E] single-token activations."""
        c = self.config
        dt = c.dtype
        t = ctx.tensor if ctx else None
        f_ = ctx.fsdp if ctx else None
        h = _rms_norm(x, p["ln2"]["scale"], c.rms_norm_eps)
        h = _maybe_megatron_f(h, ctx)
        wg = _maybe(unshard_fsdp, p["mlp"]["wg"], f_, 0).astype(dt)
        wu = _maybe(unshard_fsdp, p["mlp"]["wu"], f_, 0).astype(dt)
        g = jax.nn.silu(h @ wg) * (h @ wu)
        wo = _maybe(unshard_fsdp, p["mlp"]["wo"], f_, 1).astype(dt)
        out = g @ wo
        return x + _maybe_reduce(out, t, ctx)

    def head(self, p, x, ctx: ShardCtx | None = None):
        c = self.config
        x = _rms_norm(x, p["ln_f"]["scale"], c.rms_norm_eps)
        logits = (x @ p["w"].astype(c.dtype)).astype(jnp.float32)
        if ctx and ctx.tensor:
            logits = lax.all_gather(logits, ctx.tensor, axis=-1, tiled=True)
        mask = jnp.arange(logits.shape[-1]) < c.vocab_size
        return jnp.where(mask, logits, NEG_INF)

    def head_loss_shifted(self, p, x, targets, mask, ctx: ShardCtx | None = None):
        c = self.config
        x = _rms_norm(x, p["ln_f"]["scale"], c.rms_norm_eps)
        x = _maybe_megatron_f(x, ctx)
        local_logits = (x @ p["w"].astype(c.dtype)).astype(jnp.float32)
        vlocal = local_logits.shape[-1]
        offset = (ctx.tp_rank() * vlocal) if (ctx and ctx.tensor) else 0
        col_ids = jnp.arange(vlocal) + offset
        local_logits = jnp.where(col_ids < c.vocab_size, local_logits, NEG_INF)
        per_pos = vocab_parallel_logits_loss(
            local_logits, targets, offset, ctx.tensor if ctx else None,
            identity_bwd=_explicit_bwd(ctx),
        )
        return jnp.sum(per_pos * mask)

    def forward(self, params, tokens):
        c = self.config
        x = self.embed(params["embed"], tokens)
        block = self.apply_block
        if c.remat:
            block = jax.checkpoint(block)

        def body(x, bp):
            return block(bp, x), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
        return self.head(params["head"], x)

    def loss(self, params, batch):
        return self.loss_from_logits(self.forward(params, batch["input_ids"]), batch)

    # ---- incremental decode (serving) ----

    def init_kv_cache(self, batch_size: int, max_seq: int, dtype=None):
        """Preallocated KV cache [L, B, KV, S, D] — unrepeated KV heads;
        decode folds query heads into groups against it (GQA caches 1/rep
        the bytes of the repeated form)."""
        c = self.config
        shape = (c.num_layers, batch_size, c.kv_heads, max_seq, c.head_dim)
        dt = c.dtype if dtype is None else dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def _decode_attention_sublayer(self, p, x, k_cache, v_cache, pos):
        """attention_sublayer for ONE new token per slot against the KV
        cache. x [B, E]; k_cache/v_cache [B, KV, S, D]; pos [B]."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.attention import cache_write, decode_attention

        h = _rms_norm(x, p["ln1"]["scale"], c.rms_norm_eps)
        q = jnp.einsum("be,ehd->bhd", h, p["attn"]["wq"].astype(dt))
        kv = jnp.einsum("be,ekhd->kbhd", h, p["attn"]["wkv"].astype(dt))
        q = _rope_one(q, pos, c.rope_theta)
        k = _rope_one(kv[0], pos, c.rope_theta)
        k_cache = cache_write(k_cache, k, pos)
        v_cache = cache_write(v_cache, kv[1], pos)
        attn = decode_attention(q, k_cache, v_cache, pos)  # GQA folded inside
        out = jnp.einsum("bhd,hde->be", attn, p["attn"]["wo"].astype(dt))
        return x + out, k_cache, v_cache

    def forward_prefill(self, params, tokens, kv_cache, slot, length):
        """Prompt pass for ONE request into batch slot `slot`; same contract
        as GPTModel.forward_prefill (tokens [1, T] possibly padded past
        `length`; returns next-token logits [V] f32 + updated cache)."""
        x = self.embed(params["embed"], tokens)

        def body(x, bp):
            x, k, v = self.attention_sublayer(bp, x, return_kv=True)
            return self.mlp_sublayer(bp, x), (k, v)

        x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
        k_cache = lax.dynamic_update_slice(
            kv_cache["k"], ks.astype(kv_cache["k"].dtype), (0, slot, 0, 0, 0))
        v_cache = lax.dynamic_update_slice(
            kv_cache["v"], vs.astype(kv_cache["v"].dtype), (0, slot, 0, 0, 0))
        logits = self.head(params["head"], x)[0, length - 1]
        return logits, {"k": k_cache, "v": v_cache}

    def forward_decode(self, params, token, kv_cache, pos):
        """One decode step over all slots; same contract as
        GPTModel.forward_decode (token [B], pos [B] -> logits [B, V] f32)."""
        x = params["embed"]["wte"][token].astype(self.config.dtype)

        def body(x, sl):
            bp, kc, vc = sl
            x, kc, vc = self._decode_attention_sublayer(bp, x, kc, vc, pos)
            return self.mlp_sublayer(bp, x), (kc, vc)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        logits = self.head(params["head"], x[:, None, :])[:, 0]
        return logits, {"k": k_new, "v": v_new}

    # ---- paged incremental decode (serving, block-table KV) ----

    def init_paged_kv_cache(self, num_pages: int, page_size: int, dtype=None):
        """Paged KV pool [L, N_pages, KV, page, D] — unrepeated KV heads,
        post-RoPE keys (absolute phases baked in, so gathered head pages
        are position-correct without recompute). Page 0 is the reserved
        garbage page."""
        c = self.config
        shape = (c.num_layers, num_pages, c.kv_heads, page_size, c.head_dim)
        dt = c.dtype if dtype is None else dtype
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def _paged_impl(self) -> str:
        impl = self.config.attention_impl
        return impl if impl in ("xla", "pallas") else "auto"

    def _paged_decode_sublayer(self, p, x, k_pool, v_pool, block_tables, pos):
        """_decode_attention_sublayer against a page pool; GQA folds query
        heads inside paged_decode_attention against the unrepeated pool."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.paged_attention import (
            paged_cache_write, paged_decode_attention)

        h = _rms_norm(x, p["ln1"]["scale"], c.rms_norm_eps)
        q = jnp.einsum("be,ehd->bhd", h, p["attn"]["wq"].astype(dt))
        kv = jnp.einsum("be,ekhd->kbhd", h, p["attn"]["wkv"].astype(dt))
        q = _rope_one(q, pos, c.rope_theta)
        k = _rope_one(kv[0], pos, c.rope_theta)
        k_pool = paged_cache_write(k_pool, k, block_tables, pos)
        v_pool = paged_cache_write(v_pool, kv[1], block_tables, pos)
        attn = paged_decode_attention(q, k_pool, v_pool, block_tables, pos + 1,
                                      impl=self._paged_impl())
        out = jnp.einsum("bhd,hde->be", attn, p["attn"]["wo"].astype(dt))
        return x + out, k_pool, v_pool

    def _tail_prefill_sublayer(self, p, x, k_pool, v_pool, head_tables,
                               prior_len):
        """Prompt-tail attention over a gathered cached head (see
        GPTModel._tail_prefill_sublayer): head pages hold post-RoPE K, so
        the prefix hit skips the head's compute; tail queries/keys rotate
        at absolute positions prior_len + i; mask is explicit."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.attention import _xla_causal_attention
        from oobleck_tpu.ops.paged_attention import paged_gather_kv

        h = _rms_norm(x, p["ln1"]["scale"], c.rms_norm_eps)
        wq = p["attn"]["wq"].astype(dt)
        wkv = p["attn"]["wkv"].astype(dt)
        q = jnp.einsum("bse,ehd->bhsd", h, wq)
        kv = jnp.einsum("bse,ekhd->kbhsd", h, wkv)
        t_len = q.shape[2]
        pos = prior_len + jnp.arange(t_len)
        q = _rope(q, pos, c.rope_theta)
        k_tail = _rope(kv[0], pos, c.rope_theta)
        v_tail = kv[1]
        head_k = paged_gather_kv(k_pool, head_tables[None]).astype(dt)
        head_v = paged_gather_kv(v_pool, head_tables[None]).astype(dt)
        k = jnp.concatenate([head_k, k_tail], axis=2)
        v = jnp.concatenate([head_v, v_tail], axis=2)
        if c.kv_heads != c.num_heads:
            rep = c.num_heads // c.kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        s_head = head_k.shape[2]
        live = jnp.concatenate([
            jnp.broadcast_to(jnp.arange(s_head) < prior_len, (t_len, s_head)),
            jnp.tril(jnp.ones((t_len, t_len), bool)),
        ], axis=1)
        bias = jnp.where(live, 0.0, NEG_INF)[None]                      # [1,T,S]
        attn = _xla_causal_attention(q, k, v, bias=bias, causal=False)
        out = jnp.einsum("bhsd,hde->bse", attn, p["attn"]["wo"].astype(dt))
        return x + out, k_tail, v_tail

    def forward_prefill_paged(self, params, tokens, kv_cache, block_tables,
                              length, head_tables=None, prior_len=0):
        """Same contract as GPTModel.forward_prefill_paged (prompt tail into
        pool pages, optional cached head via head_tables/prior_len)."""
        from oobleck_tpu.models.gpt import GPTModel

        c = self.config
        prior_len = jnp.asarray(prior_len, jnp.int32)
        x = params["embed"]["wte"][tokens].astype(c.dtype)

        def body(x, sl):
            bp, kp, vp = sl
            if head_tables is None:
                x, k, v = self.attention_sublayer(bp, x, return_kv=True)
            else:
                x, k, v = self._tail_prefill_sublayer(
                    bp, x, kp, vp, head_tables, prior_len)
            return self.mlp_sublayer(bp, x), (k, v)

        x, (ks, vs) = jax.lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        kv_cache = GPTModel._paged_tail_write(
            self, kv_cache, ks, vs, block_tables, prior_len, length)
        logits = self.head(params["head"], x)[0, length - 1]
        return logits, kv_cache

    def forward_decode_paged(self, params, token, kv_cache, block_tables, pos):
        """Same contract as GPTModel.forward_decode_paged."""
        x = params["embed"]["wte"][token].astype(self.config.dtype)

        def body(x, sl):
            bp, kp, vp = sl
            x, kp, vp = self._paged_decode_sublayer(
                bp, x, kp, vp, block_tables, pos)
            return self.mlp_sublayer(bp, x), (kp, vp)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        logits = self.head(params["head"], x[:, None, :])[:, 0]
        return logits, {"k": k_new, "v": v_new}

    def _paged_verify_sublayer(self, p, x, k_pool, v_pool, block_tables,
                               pos, n_live):
        """_paged_decode_sublayer for T speculative tokens per lane (see
        GPTModel._paged_verify_sublayer): queries and keys rotate at their
        true absolute positions pos + i, K/V for all T candidates scatter
        through the block table (padding to the garbage page), and GQA
        folds query heads inside paged_verify_attention."""
        c = self.config
        dt = c.dtype
        from oobleck_tpu.ops.paged_attention import (
            paged_cache_write_multi, paged_verify_attention)

        h = _rms_norm(x, p["ln1"]["scale"], c.rms_norm_eps)             # [B,T,E]
        q = jnp.einsum("bte,ehd->bhtd", h, p["attn"]["wq"].astype(dt))
        kv = jnp.einsum("bte,ekhd->kbhtd", h, p["attn"]["wkv"].astype(dt))
        t_len = x.shape[1]
        pos_abs = pos[:, None] + jnp.arange(t_len)                      # [B,T]
        q = _rope_multi(q, pos_abs, c.rope_theta)
        k = _rope_multi(kv[0], pos_abs, c.rope_theta)
        k_pool = paged_cache_write_multi(
            k_pool, k.transpose(0, 2, 1, 3), block_tables, pos, n_live)
        v_pool = paged_cache_write_multi(
            v_pool, kv[1].transpose(0, 2, 1, 3), block_tables, pos, n_live)
        attn = paged_verify_attention(
            q.transpose(0, 2, 1, 3), k_pool, v_pool, block_tables, pos + 1,
            impl=self._paged_impl())
        out = jnp.einsum("bthd,hde->bte", attn, p["attn"]["wo"].astype(dt))
        return x + out, k_pool, v_pool

    def forward_verify_paged(self, params, tokens, kv_cache, block_tables,
                             pos, n_live):
        """Same contract as GPTModel.forward_verify_paged (T candidate
        tokens per lane at absolute positions, post-RoPE keys cached)."""
        x = params["embed"]["wte"][tokens].astype(self.config.dtype)

        def body(x, sl):
            bp, kp, vp = sl
            x, kp, vp = self._paged_verify_sublayer(
                bp, x, kp, vp, block_tables, pos, n_live)
            return self.mlp_sublayer(bp, x), (kp, vp)

        x, (k_new, v_new) = jax.lax.scan(
            body, x, (params["blocks"], kv_cache["k"], kv_cache["v"]))
        logits = self.head(params["head"], x)
        return logits, {"k": k_new, "v": v_new}

    # ---- sharding ----

    def param_specs(self, *, stacked: bool = True):
        s = ("stage",) if stacked else ()
        block = {
            "ln1": {"scale": P(*s)},
            "attn": {
                "wq": P(*s, "fsdp", "tensor", None),
                "wkv": P(*s, "fsdp", None, "tensor", None),
                "wo": P(*s, "tensor", None, "fsdp"),
            },
            "ln2": {"scale": P(*s)},
            "mlp": {
                "wg": P(*s, "fsdp", "tensor"),
                "wu": P(*s, "fsdp", "tensor"),
                "wo": P(*s, "tensor", "fsdp"),
            },
        }
        return {
            "embed": {"wte": P("tensor", None)},
            "blocks": block,
            "head": {"ln_f": {"scale": P()}, "w": P(None, "tensor")},
        }
