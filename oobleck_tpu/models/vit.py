"""ViT image classifier as an explicit layer list.

Capability match for the reference's image families (vit/swin via
AutoModelForImageClassification, /root/reference/oobleck/module/model.py:26-30,
sharding.py:31-34): patch embedding, bidirectional transformer blocks, CLS
classification head with cross-entropy.

Layer list: [patch_embed, block_0.., head] — the same planning/pipeline
granularity contract as the language families.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp

from oobleck_tpu.models.base import stack_layer_params
from oobleck_tpu.models.gpt import _layer_norm
from oobleck_tpu.models.bert import BertConfig, BertModel


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    num_classes: int = 1000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int | None = None
    layer_norm_epsilon: float = 1e-6
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def ffn_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def override(self, **kwargs) -> "ViTConfig":
        unknown = [k for k in kwargs if k not in ViTConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        return replace(self, **kwargs)


class ViTModel:
    """Reuses the BERT encoder block (bidirectional attention) with a patch
    embed front and a CLS classifier head."""

    # Engine contract: image batches (pixel_values / labels) drive the MPMD
    # pipeline through the generic apply_layer / loss_from_logits path.
    data_kind = "image"

    def __init__(self, config: ViTConfig):
        self.config = config
        # Encoder blocks are BERT blocks of the same width.
        self._encoder = BertModel(BertConfig(
            hidden_size=config.hidden_size, num_layers=config.num_layers,
            num_heads=config.num_heads,
            intermediate_size=config.intermediate_size,
            layer_norm_epsilon=config.layer_norm_epsilon,
            dtype=config.dtype, param_dtype=config.param_dtype,
        ))

    @property
    def num_pipeline_layers(self) -> int:
        return self.config.num_layers + 2

    def layer_name(self, index: int) -> str:
        if index == 0:
            return "embed"
        if index == self.num_pipeline_layers - 1:
            return "head"
        return f"block_{index - 1}"

    def init_layer(self, rng, index):
        ks = jax.random.split(rng, 3)
        if index == 0:
            return self._init_embed(ks[0])
        if index == self.num_pipeline_layers - 1:
            return self._init_head(ks[2])
        return self._encoder._init_block(jax.random.fold_in(ks[1], index))

    def apply_layer(self, index, params, carry, batch, ctx=None):
        if index == 0:
            return self.embed(params, batch["pixel_values"])
        if index == self.num_pipeline_layers - 1:
            return self.head(params, carry)
        return self._encoder.apply_block(params, carry)

    def sample_batch(self, batch_size: int, *_ignored):
        c = self.config
        rng = jax.random.PRNGKey(0)
        return {
            "pixel_values": jax.random.normal(
                rng, (batch_size, c.image_size, c.image_size, c.num_channels),
                jnp.float32,
            ),
            "labels": jax.random.randint(
                jax.random.fold_in(rng, 1), (batch_size,), 0, c.num_classes,
                dtype=jnp.int32,
            ),
        }

    # ---- init ----

    def _init_embed(self, rng):
        c = self.config
        k1, k2, k3 = jax.random.split(rng, 3)
        std = c.initializer_range
        patch_dim = c.patch_size * c.patch_size * c.num_channels
        return {
            "proj": jax.random.normal(k1, (patch_dim, c.hidden_size), c.param_dtype) * std,
            "bias": jnp.zeros((c.hidden_size,), c.param_dtype),
            "cls": jax.random.normal(k2, (1, 1, c.hidden_size), c.param_dtype) * std,
            "pos": jax.random.normal(
                k3, (c.num_patches + 1, c.hidden_size), c.param_dtype
            ) * std,
        }

    def _init_head(self, rng):
        c = self.config
        return {
            "ln_f": {"scale": jnp.ones((c.hidden_size,), c.param_dtype),
                     "bias": jnp.zeros((c.hidden_size,), c.param_dtype)},
            "w": jax.random.normal(rng, (c.hidden_size, c.num_classes), c.param_dtype)
            * c.initializer_range,
            "b": jnp.zeros((c.num_classes,), c.param_dtype),
        }

    def init_params(self, rng):
        ks = jax.random.split(rng, 3)
        blocks = [self._encoder._init_block(jax.random.fold_in(ks[1], i + 1))
                  for i in range(self.config.num_layers)]
        return {"embed": self._init_embed(ks[0]),
                "blocks": stack_layer_params(blocks),
                "head": self._init_head(ks[2])}

    # ---- forward ----

    def embed(self, p, pixels: jax.Array) -> jax.Array:
        """[B, H, W, C] -> [B, 1+P, E]: patchify as a reshape + matmul (the
        conv-as-matmul form XLA tiles straight onto the MXU)."""
        c = self.config
        b, hh, ww, ch = pixels.shape
        ps = c.patch_size
        x = pixels.reshape(b, hh // ps, ps, ww // ps, ps, ch)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, c.num_patches, ps * ps * ch)
        x = x.astype(c.dtype) @ p["proj"].astype(c.dtype) + p["bias"].astype(c.dtype)
        cls = jnp.broadcast_to(p["cls"].astype(c.dtype), (b, 1, c.hidden_size))
        x = jnp.concatenate([cls, x], axis=1)
        return x + p["pos"].astype(c.dtype)

    def head(self, p, x: jax.Array) -> jax.Array:
        c = self.config
        cls = _layer_norm(x[:, 0], p["ln_f"]["scale"], p["ln_f"]["bias"],
                          c.layer_norm_epsilon)
        return (cls @ p["w"].astype(c.dtype) + p["b"].astype(c.dtype)).astype(jnp.float32)

    def forward(self, params, pixels):
        block = self._encoder.apply_block
        if self.config.remat:
            block = jax.checkpoint(block)
        x = self.embed(params["embed"], pixels)

        def body(x, bp):
            return block(bp, x), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
        return self.head(params["head"], x)

    def loss_from_logits(self, logits, batch):
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, batch["labels"][..., None], axis=-1
        )[..., 0]
        return jnp.mean(logz - gold)


    def accuracy_from_logits(self, logits, batch):
        from oobleck_tpu.models.base import argmax_accuracy

        return argmax_accuracy(logits, batch["labels"])

    def loss(self, params, batch):
        return self.loss_from_logits(
            self.forward(params, batch["pixel_values"]), batch
        )
