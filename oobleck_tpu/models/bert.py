"""BERT-family encoder as an explicit layer list.

Capability match for the reference's bert path (HF AutoModelForMaskedLM +
fx split points per encoder block, /root/reference/oobleck/module/
model.py:21-33, sharding.py:19-22): bidirectional attention, learned
positions, masked-language-modeling objective.

Same layer-list contract as GPT ([embed, block_0.., head]); blocks reuse the
GPT block shape with `causal=False` attention. MLM batches are produced by
`make_mlm_batch` (corrupt 15% of tokens: 80% [MASK], 10% random, 10% kept);
the loss runs only over corrupted positions.

Engine integration: the MPMD pipeline drives BERT through the generic
apply_layer / loss_from_logits contract with MLMView batches (corruption
done dataset-side); the fused SPMD step remains causal-LM-specific.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from oobleck_tpu.models.base import stack_layer_params
from oobleck_tpu.models.gpt import NEG_INF, ShardCtx, _layer_norm
from oobleck_tpu.ops.attention import _xla_causal_attention


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int | None = None
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    mask_token_id: int = 103  # HF bert [MASK]
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def ffn_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def override(self, **kwargs) -> "BertConfig":
        alias = {"n_embd": "hidden_size", "n_layer": "num_layers",
                 "n_head": "num_heads", "n_positions": "max_position_embeddings"}
        kwargs = {alias.get(k, k): v for k, v in kwargs.items()}
        unknown = [k for k in kwargs if k not in BertConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        return replace(self, **kwargs)


class BertModel:
    # Engine contract: batches carry pre-corrupted inputs + labels + mask
    # (execution.dataset.MLMView); the MPMD pipeline drives apply_layer +
    # loss_from_logits. The fused SPMD step is causal-LM-specific.
    data_kind = "mlm"

    def __init__(self, config: BertConfig):
        self.config = config

    @property
    def num_pipeline_layers(self) -> int:
        return self.config.num_layers + 2

    def layer_name(self, index: int) -> str:
        if index == 0:
            return "embed"
        if index == self.num_pipeline_layers - 1:
            return "head"
        return f"block_{index - 1}"

    def init_layer(self, rng, index):
        ks = jax.random.split(rng, 3)
        if index == 0:
            return self._init_embed(ks[0])
        if index == self.num_pipeline_layers - 1:
            return self._init_head(ks[2])
        return self._init_block(jax.random.fold_in(ks[1], index))

    def apply_layer(self, index, params, carry, batch, ctx=None):
        if index == 0:
            return self.embed(params, batch["input_ids"])
        if index == self.num_pipeline_layers - 1:
            return self.head(params, carry)
        return self.apply_block(params, carry)

    def loss_from_logits(self, logits, batch):
        """Masked-LM loss over corrupted positions. `batch` carries
        pre-corrupted input_ids plus the clean labels and the float mask of
        corrupted positions (MLMView's contract)."""
        labels = batch["labels"]
        mask = batch["loss_mask"].astype(jnp.float32)
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        per_pos = (logz - gold) * mask
        return jnp.sum(per_pos) / jnp.maximum(jnp.sum(mask), 1.0)

    def accuracy_from_logits(self, logits, batch):
        """Masked-token accuracy over the corrupted positions (reference
        accuracy metric parity, dataset.py:39-54)."""
        mask = batch["loss_mask"].astype(jnp.float32)
        pred = jnp.argmax(logits, axis=-1)
        correct = (pred == batch["labels"]).astype(jnp.float32) * mask
        return jnp.sum(correct), jnp.sum(mask)

    def sample_batch(self, batch_size: int, seq_len: int):
        tokens = jax.random.randint(
            jax.random.PRNGKey(0), (batch_size, seq_len), 0,
            self.config.vocab_size, dtype=jnp.int32,
        )
        corrupted, labels, mask = self.make_mlm_batch(
            tokens, jax.random.PRNGKey(1)
        )
        return {"input_ids": corrupted, "labels": labels, "loss_mask": mask}

    # ---- init (GPT block shapes + ln_embed) ----

    def _init_embed(self, rng):
        c = self.config
        k1, k2 = jax.random.split(rng)
        std = c.initializer_range
        return {
            "wte": jax.random.normal(k1, (c.vocab_size, c.hidden_size), c.param_dtype) * std,
            "wpe": jax.random.normal(k2, (c.max_position_embeddings, c.hidden_size), c.param_dtype) * std,
            "ln": {"scale": jnp.ones((c.hidden_size,), c.param_dtype),
                   "bias": jnp.zeros((c.hidden_size,), c.param_dtype)},
        }

    def _init_block(self, rng):
        c = self.config
        ks = jax.random.split(rng, 4)
        std = c.initializer_range
        e, f, h, d = c.hidden_size, c.ffn_dim, c.num_heads, c.head_dim
        return {
            "ln1": {"scale": jnp.ones((e,), c.param_dtype), "bias": jnp.zeros((e,), c.param_dtype)},
            "attn": {
                "wqkv": jax.random.normal(ks[0], (e, 3, h, d), c.param_dtype) * std,
                "bqkv": jnp.zeros((3, h, d), c.param_dtype),
                "wo": jax.random.normal(ks[1], (h, d, e), c.param_dtype) * std,
                "bo": jnp.zeros((e,), c.param_dtype),
            },
            "ln2": {"scale": jnp.ones((e,), c.param_dtype), "bias": jnp.zeros((e,), c.param_dtype)},
            "mlp": {
                "wi": jax.random.normal(ks[2], (e, f), c.param_dtype) * std,
                "bi": jnp.zeros((f,), c.param_dtype),
                "wo": jax.random.normal(ks[3], (f, e), c.param_dtype) * std,
                "bo": jnp.zeros((e,), c.param_dtype),
            },
        }

    def _init_head(self, rng):
        c = self.config
        return {
            "ln_f": {"scale": jnp.ones((c.hidden_size,), c.param_dtype),
                     "bias": jnp.zeros((c.hidden_size,), c.param_dtype)},
            "w": jax.random.normal(
                rng, (c.hidden_size, c.vocab_size), c.param_dtype
            ) * c.initializer_range,
        }

    def init_params(self, rng):
        ks = jax.random.split(rng, 3)
        blocks = [self._init_block(jax.random.fold_in(ks[1], i + 1))
                  for i in range(self.config.num_layers)]
        return {"embed": self._init_embed(ks[0]),
                "blocks": stack_layer_params(blocks),
                "head": self._init_head(ks[2])}

    # ---- forward (bidirectional) ----

    def embed(self, p, tokens):
        c = self.config
        x = p["wte"][tokens] + p["wpe"][: tokens.shape[-1]]
        x = _layer_norm(x, p["ln"]["scale"], p["ln"]["bias"], c.layer_norm_epsilon)
        return x.astype(c.dtype)

    def apply_block(self, p, x):
        c = self.config
        dt = c.dtype
        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"], c.layer_norm_epsilon)
        qkv = jnp.einsum("bse,ethd->tbhsd", h, p["attn"]["wqkv"].astype(dt))
        qkv = qkv + p["attn"]["bqkv"].astype(dt)[:, None, :, None, :]
        attn = _xla_causal_attention(qkv[0], qkv[1], qkv[2], causal=False)
        out = jnp.einsum("bhsd,hde->bse", attn, p["attn"]["wo"].astype(dt))
        x = x + out + p["attn"]["bo"].astype(dt)
        h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"], c.layer_norm_epsilon)
        h = jax.nn.gelu(h @ p["mlp"]["wi"].astype(dt) + p["mlp"]["bi"].astype(dt))
        return x + h @ p["mlp"]["wo"].astype(dt) + p["mlp"]["bo"].astype(dt)

    def head(self, p, x):
        c = self.config
        x = _layer_norm(x, p["ln_f"]["scale"], p["ln_f"]["bias"], c.layer_norm_epsilon)
        return (x @ p["w"].astype(c.dtype)).astype(jnp.float32)

    def forward(self, params, tokens):
        block = self.apply_block
        if self.config.remat:
            block = jax.checkpoint(block)
        x = self.embed(params["embed"], tokens)

        def body(x, bp):
            return block(bp, x), None

        x, _ = jax.lax.scan(body, x, params["blocks"])
        return self.head(params["head"], x)

    # ---- MLM objective ----

    def make_mlm_batch(self, tokens: jax.Array, rng: jax.Array):
        """Corrupt 15% of positions (80% [MASK] / 10% random / 10% kept);
        returns (corrupted, labels, loss_mask). jit-safe (pure jax ops)."""
        c = self.config
        k1, k2, k3 = jax.random.split(rng, 3)
        select = jax.random.uniform(k1, tokens.shape) < 0.15
        roll = jax.random.uniform(k2, tokens.shape)
        randoms = jax.random.randint(k3, tokens.shape, 0, c.vocab_size,
                                     dtype=tokens.dtype)
        corrupted = jnp.where(select & (roll < 0.8), c.mask_token_id, tokens)
        corrupted = jnp.where(select & (roll >= 0.8) & (roll < 0.9),
                              randoms, corrupted)
        return corrupted, tokens, select.astype(jnp.float32)

    def mlm_loss(self, params, corrupted, labels, mask):
        logits = self.forward(params, corrupted)
        return self.loss_from_logits(
            logits, {"labels": labels, "loss_mask": mask}
        )

    def loss(self, params, batch, rng: jax.Array | None = None):
        """MLM loss. Pass a fresh `rng` per step so the corruption mask
        varies; the deterministic default is for tests only."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        corrupted, labels, mask = self.make_mlm_batch(batch["input_ids"], rng)
        return self.mlm_loss(params, corrupted, labels, mask)
