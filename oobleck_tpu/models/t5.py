"""T5 encoder-decoder as an explicit layer list.

Capability match for the reference's t5 path (AutoModelForSeq2SeqLM + fx
split at encoder and decoder block boundaries, /root/reference/oobleck/
module/model.py:21-33, sharding.py:23-28).

Layer list (pipeline units):
    [embed, enc_0 .. enc_{Le-1}, bridge, dec_0 .. dec_{Ld-1}, head]
The `bridge` finalizes the encoder (final norm) and embeds the decoder
inputs; decoder stages carry (enc_out, y) so cross-attention needs no
side-channel — the pair flows through stage-to-stage transfers like any
activation.

Architecture: T5.1.1 style — RMS-ish T5 layer norm (no mean subtraction, no
bias), gated-GELU FF, no biases, relative position biases. Deviation from HF:
each block owns its relative-bias table instead of sharing layer 0's, keeping
layers self-contained for pipeline splitting (a few extra KB per layer).

Objective: teacher-forced seq2seq cross-entropy (decoder inputs = targets
shifted right with pad start).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from oobleck_tpu.models.base import stack_layer_params
from oobleck_tpu.ops.attention import _xla_causal_attention

NEG_INF = -1e9


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 768
    num_layers: int = 12            # encoder blocks
    num_decoder_layers: int = 12
    num_heads: int = 12
    d_ff: int | None = None
    rel_buckets: int = 32
    rel_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    initializer_range: float = 0.02
    pad_token_id: int = 0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    @property
    def ffn_dim(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    def override(self, **kwargs) -> "T5Config":
        unknown = [k for k in kwargs if k not in T5Config.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        return replace(self, **kwargs)


def _t5_norm(x, scale, eps):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale).astype(dtype)


def _rel_bucket(rel_pos, bidirectional: bool, num_buckets: int, max_dist: int):
    """T5 relative-position bucketing (log-spaced beyond half range)."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    log_ratio = jnp.log(n.astype(jnp.float32) / max_exact + 1e-6) / np.log(
        max_dist / max_exact
    )
    large = max_exact + (log_ratio * (num_buckets - max_exact)).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


def _rel_bias(table: jax.Array, q_len: int, k_len: int, bidirectional: bool,
              num_buckets: int, max_dist: int) -> jax.Array:
    """[H, q, k] additive attention bias from a [buckets, H] table."""
    ctx = jnp.arange(q_len)[:, None]
    mem = jnp.arange(k_len)[None, :]
    buckets = _rel_bucket(mem - ctx, bidirectional, num_buckets, max_dist)
    return table[buckets].transpose(2, 0, 1)


class T5Model:
    # Engine contract: seq2seq batches (input_ids / decoder_input_ids /
    # labels) drive the MPMD pipeline generically; the bridge layer consumes
    # the batch mid-pipeline, so batch_layers lists it for stage placement.
    data_kind = "seq2seq"

    def __init__(self, config: T5Config):
        self.config = config

    @property
    def batch_layers(self) -> set[int]:
        """Layers that read `batch` (beyond the default first/last): the
        bridge starts the decoder stream from decoder_input_ids."""
        return {0, self.config.num_layers + 1, self.num_pipeline_layers - 1}

    # ---- layer list ----

    @property
    def num_pipeline_layers(self) -> int:
        c = self.config
        return 1 + c.num_layers + 1 + c.num_decoder_layers + 1

    def layer_name(self, index: int) -> str:
        c = self.config
        if index == 0:
            return "embed"
        if index <= c.num_layers:
            return f"enc_{index - 1}"
        if index == c.num_layers + 1:
            return "bridge"
        if index < self.num_pipeline_layers - 1:
            return f"dec_{index - c.num_layers - 2}"
        return "head"

    def init_layer(self, rng, index):
        # Same key derivation as init_params so the layer-list and fused
        # views of one seed produce identical weights.
        name = self.layer_name(index)
        ks = jax.random.split(rng, 5)
        c = self.config
        if name == "embed":
            return self._init_embed(ks[0])
        if name == "bridge":
            return self._init_bridge(ks[2])
        if name == "head":
            return self._init_head(ks[4])
        if name.startswith("enc_"):
            return self._init_block(jax.random.fold_in(ks[1], index), cross=False)
        dec_i = index - c.num_layers - 2
        return self._init_block(jax.random.fold_in(ks[3], dec_i + 1), cross=True)

    def apply_layer(self, index, params, carry, batch, ctx=None):
        name = self.layer_name(index)
        if name == "embed":
            return self.embed(params, batch["input_ids"])
        if name.startswith("enc_"):
            return self.apply_encoder_block(params, carry)
        if name == "bridge":
            return self.bridge(params, carry, batch["decoder_input_ids"])
        if name.startswith("dec_"):
            return self.apply_decoder_block(params, carry)
        enc_out, y = carry
        return self.head(params, y)

    def sample_batch(self, batch_size: int, seq_len: int):
        c = self.config
        rng = jax.random.PRNGKey(0)
        inputs = jax.random.randint(rng, (batch_size, seq_len), 0,
                                    c.vocab_size, dtype=jnp.int32)
        targets = jax.random.randint(jax.random.fold_in(rng, 1),
                                     (batch_size, seq_len), 0, c.vocab_size,
                                     dtype=jnp.int32)
        return {
            "input_ids": inputs,
            "labels": targets,
            "decoder_input_ids": self.shift_right(targets),
        }

    def shift_right(self, targets: jax.Array) -> jax.Array:
        c = self.config
        start = jnp.full_like(targets[..., :1], c.pad_token_id)
        return jnp.concatenate([start, targets[..., :-1]], axis=-1)

    # ---- init ----

    def _init_embed(self, rng):
        c = self.config
        return {"wte": jax.random.normal(
            rng, (c.vocab_size, c.d_model), c.param_dtype) * c.initializer_range}

    def _init_bridge(self, rng):
        c = self.config
        return {
            "enc_norm": {"scale": jnp.ones((c.d_model,), c.param_dtype)},
            "wte_dec": jax.random.normal(
                rng, (c.vocab_size, c.d_model), c.param_dtype
            ) * c.initializer_range,
        }

    def _attn_params(self, rng):
        c = self.config
        ks = jax.random.split(rng, 3)
        std = c.initializer_range
        e, h, d = c.d_model, c.num_heads, c.head_dim
        return {
            "wqkv": jax.random.normal(ks[0], (e, 3, h, d), c.param_dtype) * std,
            "wo": jax.random.normal(ks[1], (h, d, e), c.param_dtype) * std,
            "rel": jax.random.normal(ks[2], (c.rel_buckets, h), c.param_dtype) * std,
        }

    def _init_block(self, rng, cross: bool):
        c = self.config
        ks = jax.random.split(rng, 5)
        std = c.initializer_range
        e, f = c.d_model, c.ffn_dim
        out = {
            "ln1": {"scale": jnp.ones((e,), c.param_dtype)},
            "attn": self._attn_params(ks[0]),
            "ln_ff": {"scale": jnp.ones((e,), c.param_dtype)},
            "mlp": {
                "wg": jax.random.normal(ks[1], (e, f), c.param_dtype) * std,
                "wu": jax.random.normal(ks[2], (e, f), c.param_dtype) * std,
                "wo": jax.random.normal(ks[3], (f, e), c.param_dtype) * std,
            },
        }
        if cross:
            h, d = c.num_heads, c.head_dim
            xk = jax.random.split(ks[4], 3)
            out["ln_x"] = {"scale": jnp.ones((e,), c.param_dtype)}
            # Split projections: q from the decoder stream, k/v from the
            # encoder stream — a fused wqkv would compute (and discard) the
            # other stream's projections. No relative bias in cross attention.
            out["xattn"] = {
                "wq": jax.random.normal(xk[0], (e, h, d), c.param_dtype) * std,
                "wkv": jax.random.normal(xk[1], (e, 2, h, d), c.param_dtype) * std,
                "wo": jax.random.normal(xk[2], (h, d, e), c.param_dtype) * std,
            }
        return out

    def _init_head(self, rng):
        c = self.config
        return {
            "ln_f": {"scale": jnp.ones((c.d_model,), c.param_dtype)},
            "w": jax.random.normal(rng, (c.d_model, c.vocab_size), c.param_dtype)
            * c.initializer_range,
        }

    def init_params(self, rng):
        ks = jax.random.split(rng, 5)
        c = self.config
        enc = [self._init_block(jax.random.fold_in(ks[1], i + 1), cross=False)
               for i in range(c.num_layers)]
        dec = [self._init_block(jax.random.fold_in(ks[3], i + 1), cross=True)
               for i in range(c.num_decoder_layers)]
        return {
            "embed": self._init_embed(ks[0]),
            "enc_blocks": stack_layer_params(enc),
            "bridge": self._init_bridge(ks[2]),
            "dec_blocks": stack_layer_params(dec),
            "head": self._init_head(ks[4]),
        }

    # ---- forward ----

    def embed(self, p, tokens):
        return p["wte"][tokens].astype(self.config.dtype)

    def _self_attn(self, p, x, causal: bool):
        c = self.config
        dt = c.dtype
        qkv = jnp.einsum("bse,ethd->tbhsd", x, p["wqkv"].astype(dt))
        s = x.shape[1]
        bias = _rel_bias(p["rel"].astype(jnp.float32), s, s,
                         bidirectional=not causal,
                         num_buckets=c.rel_buckets,
                         max_dist=c.rel_max_distance)
        out = _xla_causal_attention(qkv[0], qkv[1], qkv[2], bias=bias,
                                    causal=causal, scale=1.0)
        return jnp.einsum("bhsd,hde->bse", out, p["wo"].astype(dt))

    def _cross_attn(self, p, y, enc_out):
        dt = self.config.dtype
        q = jnp.einsum("bse,ehd->bhsd", y, p["wq"].astype(dt))
        kv = jnp.einsum("bse,ekhd->kbhsd", enc_out, p["wkv"].astype(dt))
        out = _xla_causal_attention(q, kv[0], kv[1], causal=False, scale=1.0)
        return jnp.einsum("bhsd,hde->bse", out, p["wo"].astype(dt))

    def _ff(self, p, x):
        dt = self.config.dtype
        g = jax.nn.gelu(x @ p["wg"].astype(dt)) * (x @ p["wu"].astype(dt))
        return g @ p["wo"].astype(dt)

    def apply_encoder_block(self, p, x):
        c = self.config
        h = _t5_norm(x, p["ln1"]["scale"], c.layer_norm_epsilon)
        x = x + self._self_attn(p["attn"], h, causal=False)
        h = _t5_norm(x, p["ln_ff"]["scale"], c.layer_norm_epsilon)
        return x + self._ff(p["mlp"], h)

    def bridge(self, p, enc_x, decoder_input_ids):
        c = self.config
        enc_out = _t5_norm(enc_x, p["enc_norm"]["scale"], c.layer_norm_epsilon)
        y = p["wte_dec"][decoder_input_ids].astype(c.dtype)
        return (enc_out, y)

    def apply_decoder_block(self, p, carry):
        c = self.config
        enc_out, y = carry
        h = _t5_norm(y, p["ln1"]["scale"], c.layer_norm_epsilon)
        y = y + self._self_attn(p["attn"], h, causal=True)
        h = _t5_norm(y, p["ln_x"]["scale"], c.layer_norm_epsilon)
        y = y + self._cross_attn(p["xattn"], h, enc_out)
        h = _t5_norm(y, p["ln_ff"]["scale"], c.layer_norm_epsilon)
        y = y + self._ff(p["mlp"], h)
        return (enc_out, y)

    def head(self, p, y):
        c = self.config
        y = _t5_norm(y, p["ln_f"]["scale"], c.layer_norm_epsilon)
        # T5 scales decoder output before the (tied-shape) projection.
        y = y * (c.d_model ** -0.5)
        return (y @ p["w"].astype(c.dtype)).astype(jnp.float32)

    def forward(self, params, input_ids, decoder_input_ids):
        c = self.config
        enc_block = self.apply_encoder_block
        dec_block = self.apply_decoder_block
        if c.remat:
            enc_block = jax.checkpoint(enc_block)
            dec_block = jax.checkpoint(dec_block)

        x = self.embed(params["embed"], input_ids)
        x, _ = jax.lax.scan(lambda x, bp: (enc_block(bp, x), None), x,
                            params["enc_blocks"])
        carry = self.bridge(params["bridge"], x, decoder_input_ids)
        carry, _ = jax.lax.scan(lambda cy, bp: (dec_block(bp, cy), None),
                                carry, params["dec_blocks"])
        _, y = carry
        return self.head(params["head"], y)

    def loss_from_logits(self, logits, batch):
        labels = batch["labels"]
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(logz - gold)


    def accuracy_from_logits(self, logits, batch):
        from oobleck_tpu.models.base import argmax_accuracy

        return argmax_accuracy(logits, batch["labels"])

    def loss(self, params, batch):
        logits = self.forward(params, batch["input_ids"],
                              batch["decoder_input_ids"])
        return self.loss_from_logits(logits, batch)
