"""The model contract: an explicit layer list.

The reference obtains per-layer granularity by fx-tracing an HF model and
splitting the graph at per-architecture module boundaries
(/root/reference/oobleck/module/sharding.py:110-196,
/root/reference/oobleck/module/model.py:71-83). On TPU there is nothing to
trace: models are *defined* as a list of layers — layer 0 embeds, layers
1..N are transformer blocks, layer N+1 is the norm+head. That list is the unit
of planning (per-layer profile costs), pipeline splitting (stage = contiguous
layer range), and elastic state copy (per-layer weight broadcast).

Two views of the same parameters:

  - per-layer list (`init_layer` / `apply_layer`): used by the profiler and
    the MPMD pipeline interpreter, where each stage owns a contiguous slice.
  - fused/stacked (`init_params` / `loss`): blocks stacked on a leading
    [num_blocks, ...] axis so the SPMD pipeline can shard them over the
    `stage` mesh axis and scan over them; used by the fast path and bench.

`stack_layer_params` / `unstack_layer_params` convert between them.
"""

from __future__ import annotations

from typing import Any, Protocol

import jax
import jax.numpy as jnp

PyTree = Any


class LayerListModel(Protocol):
    """Uniform duck-typed interface every model family implements."""

    @property
    def num_pipeline_layers(self) -> int: ...

    def layer_name(self, index: int) -> str: ...

    def init_layer(self, rng: jax.Array, index: int) -> PyTree: ...

    def apply_layer(
        self, index: int, params: PyTree, carry: PyTree, batch: dict[str, jax.Array]
    ) -> PyTree: ...

    def loss_from_logits(
        self, logits: jax.Array, batch: dict[str, jax.Array]
    ) -> jax.Array: ...

    def sample_batch(self, batch_size: int, seq_len: int) -> dict[str, jax.Array]: ...


def stack_layer_params(layer_params: list[PyTree]) -> PyTree:
    """Stack homogeneous per-layer pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *layer_params)


def unstack_layer_params(stacked: PyTree) -> list[PyTree]:
    """Inverse of stack_layer_params."""
    num = jax.tree.leaves(stacked)[0].shape[0]
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(num)]


def param_count(params: PyTree) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def param_bytes(params: PyTree) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def argmax_accuracy(logits, labels):
    """Shared task metric for evaluate() (the reference builds an accuracy
    metric via `evaluate` but never reports it, dataset.py:39-54): returns
    (correct_count, total_count) for argmax-vs-labels families
    (classification, seq2seq token accuracy)."""
    import jax.numpy as jnp

    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels).astype(jnp.float32)
    return jnp.sum(correct), jnp.float32(correct.size)
