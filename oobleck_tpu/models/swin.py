"""Swin Transformer image classifier as an explicit layer list.

Capability match for the reference's swin family (listed in its tested image
models, /root/reference/oobleck/module/model.py:21-33, loaded via
AutoModelForImageClassification; the reference's fx splitter has no swin
branch — sharding.py:12-47 — so this implementation EXCEEDS the reference,
which would assert on swin).

Layer list: [patch_embed, stage-major swin blocks with patch-merging layers
between stages, head]:
    [embed, s0_b0..s0_b{d0-1}, merge1, s1_b0.., merge2, ..., head]
Every unit is a pipeline layer; activations stay [B, H*W, C] tokens with
per-layer static (H, W) known from the index — shapes shrink 2x spatially
and grow 2x in channels at each merge, which the MPMD pipeline handles as
per-stage static shapes.

Swin semantics implemented: windowed multi-head attention with relative
position bias, alternating shifted windows (roll + cross-window attention
mask), patch merging (2x2 concat + linear reduction), pre-norm MLP blocks,
global-average-pool head.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from oobleck_tpu.models.gpt import _layer_norm

NEG_INF = -1e9


@dataclass(frozen=True)
class SwinConfig:
    image_size: int = 224
    patch_size: int = 4
    num_channels: int = 3
    num_classes: int = 1000
    embed_dim: int = 96
    depths: tuple = (2, 2, 6, 2)
    num_heads: tuple = (3, 6, 12, 24)
    window_size: int = 7
    mlp_ratio: float = 4.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True

    def override(self, **kwargs) -> "SwinConfig":
        unknown = [k for k in kwargs if k not in SwinConfig.__dataclass_fields__]
        if unknown:
            raise ValueError(f"unknown model_args {unknown}")
        for key in ("depths", "num_heads"):
            if key in kwargs:
                kwargs[key] = tuple(kwargs[key])
        return replace(self, **kwargs)


@functools.lru_cache(maxsize=64)
def _rel_index(window: int) -> np.ndarray:
    """[w*w, w*w] indices into the (2w-1)^2 relative-bias table."""
    coords = np.stack(np.meshgrid(np.arange(window), np.arange(window),
                                  indexing="ij")).reshape(2, -1)
    rel = coords[:, :, None] - coords[:, None, :]  # [2, w*w, w*w]
    rel = rel + (window - 1)
    return rel[0] * (2 * window - 1) + rel[1]


@functools.lru_cache(maxsize=64)
def _shift_mask(hw: int, window: int, shift: int) -> np.ndarray:
    """[num_windows, w*w, w*w] additive mask for shifted-window attention:
    tokens that wrapped around via the roll must not attend across the
    original image boundary (the standard swin region-id mask)."""
    img = np.zeros((hw, hw), np.int32)
    slices = (slice(0, -window), slice(-window, -shift), slice(-shift, None))
    cnt = 0
    for hs in slices:
        for ws in slices:
            img[hs, ws] = cnt
            cnt += 1
    n = hw // window
    wins = img.reshape(n, window, n, window).transpose(0, 2, 1, 3)
    wins = wins.reshape(n * n, window * window)
    same = wins[:, :, None] == wins[:, None, :]
    return np.where(same, 0.0, NEG_INF).astype(np.float32)


class SwinModel:
    data_kind = "image"

    def __init__(self, config: SwinConfig):
        self.config = config
        if config.image_size % config.patch_size != 0:
            raise ValueError("image_size must divide by patch_size")
        # Unit list in pipeline order: ("block", stage, j) | ("merge", stage).
        self._units: list[tuple] = []
        for s, depth in enumerate(config.depths):
            if s > 0:
                self._units.append(("merge", s))
            for j in range(depth):
                self._units.append(("block", s, j))
        base = config.image_size // config.patch_size
        self._grid = [base // (2 ** s) for s in range(len(config.depths))]
        for s, g in enumerate(self._grid):
            if g % config.window_size != 0 and g > config.window_size:
                raise ValueError(
                    f"stage {s} grid {g} not divisible by window "
                    f"{config.window_size}"
                )

    def _dim(self, s: int) -> int:
        return self.config.embed_dim * (2 ** s)

    # ---- layer list ----

    @property
    def num_pipeline_layers(self) -> int:
        return len(self._units) + 2

    def layer_name(self, index: int) -> str:
        if index == 0:
            return "embed"
        if index == self.num_pipeline_layers - 1:
            return "head"
        u = self._units[index - 1]
        return (f"stage{u[1]}_block{u[2]}" if u[0] == "block"
                else f"merge{u[1]}")

    def init_layer(self, rng, index):
        ks = jax.random.split(rng, 3)
        if index == 0:
            return self._init_embed(ks[0])
        if index == self.num_pipeline_layers - 1:
            return self._init_head(ks[2])
        u = self._units[index - 1]
        r = jax.random.fold_in(ks[1], index)
        if u[0] == "merge":
            return self._init_merge(r, u[1])
        return self._init_block(r, u[1])

    def apply_layer(self, index, params, carry, batch, ctx=None):
        if index == 0:
            return self.embed(params, batch["pixel_values"])
        if index == self.num_pipeline_layers - 1:
            return self.head(params, carry)
        u = self._units[index - 1]
        if u[0] == "merge":
            return self.merge(params, carry, u[1])
        s, j = u[1], u[2]
        return self.apply_block(params, carry, s, shifted=bool(j % 2))

    def sample_batch(self, batch_size: int, *_ignored):
        c = self.config
        rng = jax.random.PRNGKey(0)
        return {
            "pixel_values": jax.random.normal(
                rng, (batch_size, c.image_size, c.image_size, c.num_channels),
                jnp.float32,
            ),
            "labels": jax.random.randint(
                jax.random.fold_in(rng, 1), (batch_size,), 0, c.num_classes,
                dtype=jnp.int32,
            ),
        }

    # ---- init ----

    def _init_embed(self, rng):
        c = self.config
        patch_dim = c.patch_size * c.patch_size * c.num_channels
        k1, _ = jax.random.split(rng)
        return {
            "proj": jax.random.normal(
                k1, (patch_dim, c.embed_dim), c.param_dtype
            ) * c.initializer_range,
            "bias": jnp.zeros((c.embed_dim,), c.param_dtype),
            "ln": {"scale": jnp.ones((c.embed_dim,), c.param_dtype),
                   "bias": jnp.zeros((c.embed_dim,), c.param_dtype)},
        }

    def _init_block(self, rng, s: int):
        c = self.config
        e = self._dim(s)
        h = c.num_heads[s]
        f = int(e * c.mlp_ratio)
        w = min(c.window_size, self._grid[s])
        ks = jax.random.split(rng, 5)
        std = c.initializer_range
        return {
            "ln1": {"scale": jnp.ones((e,), c.param_dtype),
                    "bias": jnp.zeros((e,), c.param_dtype)},
            "attn": {
                "wqkv": jax.random.normal(ks[0], (e, 3, h, e // h),
                                          c.param_dtype) * std,
                "bqkv": jnp.zeros((3, h, e // h), c.param_dtype),
                "wo": jax.random.normal(ks[1], (h, e // h, e),
                                        c.param_dtype) * std,
                "bo": jnp.zeros((e,), c.param_dtype),
                "rel": jax.random.normal(
                    ks[2], ((2 * w - 1) ** 2, h), c.param_dtype) * std,
            },
            "ln2": {"scale": jnp.ones((e,), c.param_dtype),
                    "bias": jnp.zeros((e,), c.param_dtype)},
            "mlp": {
                "wi": jax.random.normal(ks[3], (e, f), c.param_dtype) * std,
                "bi": jnp.zeros((f,), c.param_dtype),
                "wo": jax.random.normal(ks[4], (f, e), c.param_dtype) * std,
                "bo": jnp.zeros((e,), c.param_dtype),
            },
        }

    def _init_merge(self, rng, s: int):
        c = self.config
        e_in, e_out = self._dim(s - 1), self._dim(s)
        return {
            "ln": {"scale": jnp.ones((4 * e_in,), c.param_dtype),
                   "bias": jnp.zeros((4 * e_in,), c.param_dtype)},
            "w": jax.random.normal(rng, (4 * e_in, e_out), c.param_dtype)
            * c.initializer_range,
        }

    def _init_head(self, rng):
        c = self.config
        e = self._dim(len(c.depths) - 1)
        return {
            "ln_f": {"scale": jnp.ones((e,), c.param_dtype),
                     "bias": jnp.zeros((e,), c.param_dtype)},
            "w": jax.random.normal(rng, (e, c.num_classes), c.param_dtype)
            * c.initializer_range,
            "b": jnp.zeros((c.num_classes,), c.param_dtype),
        }

    def init_params(self, rng):
        return {self.layer_name(i): self.init_layer(rng, i)
                for i in range(self.num_pipeline_layers)}

    # ---- forward ----

    def embed(self, p, pixels):
        c = self.config
        b, hh, ww, ch = pixels.shape
        ps = c.patch_size
        g = hh // ps
        x = pixels.reshape(b, g, ps, g, ps, ch)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, ps * ps * ch)
        x = x.astype(c.dtype) @ p["proj"].astype(c.dtype) + p["bias"].astype(c.dtype)
        return _layer_norm(x, p["ln"]["scale"], p["ln"]["bias"],
                           c.layer_norm_epsilon)

    def _window_attention(self, p, x, s: int, shifted: bool):
        """[B, H*W, C] -> [B, H*W, C] windowed MHA with relative bias."""
        c = self.config
        dt = c.dtype
        b, n, e = x.shape
        g = self._grid[s]
        w = min(c.window_size, g)
        shift = w // 2 if (shifted and g > w) else 0
        h = c.num_heads[s]

        x = x.reshape(b, g, g, e)
        if shift:
            x = jnp.roll(x, (-shift, -shift), axis=(1, 2))
        nw = g // w
        # [B, nw, nw, w, w, E] -> [B*nW, w*w, E]
        x = x.reshape(b, nw, w, nw, w, e).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(b * nw * nw, w * w, e)

        qkv = (jnp.einsum("bse,ethd->tbhsd", x, p["wqkv"].astype(dt))
               + p["bqkv"].astype(dt)[:, None, :, None, :])
        q, k, v = qkv[0], qkv[1], qkv[2]
        scale = (e // h) ** -0.5
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        bias = p["rel"].astype(jnp.float32)[jnp.asarray(_rel_index(w))]
        logits = logits + bias.transpose(2, 0, 1).astype(logits.dtype)
        if shift:
            mask = jnp.asarray(_shift_mask(g, w, shift))  # [nW, ws, ws]
            logits = logits.reshape(b, nw * nw, h, w * w, w * w)
            logits = logits + mask[None, :, None].astype(logits.dtype)
            logits = logits.reshape(b * nw * nw, h, w * w, w * w)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(dt)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        out = jnp.einsum("bhsd,hde->bse", out, p["wo"].astype(dt)) + p["bo"].astype(dt)

        out = out.reshape(b, nw, nw, w, w, e).transpose(0, 1, 3, 2, 4, 5)
        out = out.reshape(b, g, g, e)
        if shift:
            out = jnp.roll(out, (shift, shift), axis=(1, 2))
        return out.reshape(b, n, e)

    def apply_block(self, p, x, s: int, shifted: bool):
        c = self.config
        h = _layer_norm(x, p["ln1"]["scale"], p["ln1"]["bias"],
                        c.layer_norm_epsilon)
        x = x + self._window_attention(p["attn"], h, s, shifted)
        h = _layer_norm(x, p["ln2"]["scale"], p["ln2"]["bias"],
                        c.layer_norm_epsilon)
        h = jax.nn.gelu(h @ p["mlp"]["wi"].astype(c.dtype)
                        + p["mlp"]["bi"].astype(c.dtype))
        return x + (h @ p["mlp"]["wo"].astype(c.dtype)
                    + p["mlp"]["bo"].astype(c.dtype))

    def merge(self, p, x, s: int):
        """2x2 patch merge entering stage s: [B, g^2, E] -> [B, (g/2)^2, 2E]."""
        c = self.config
        b, n, e = x.shape
        g = self._grid[s - 1]
        x = x.reshape(b, g // 2, 2, g // 2, 2, e).transpose(0, 1, 3, 2, 4, 5)
        x = x.reshape(b, (g // 2) ** 2, 4 * e)
        x = _layer_norm(x, p["ln"]["scale"], p["ln"]["bias"],
                        c.layer_norm_epsilon)
        return x @ p["w"].astype(c.dtype)

    def head(self, p, x):
        c = self.config
        x = _layer_norm(x, p["ln_f"]["scale"], p["ln_f"]["bias"],
                        c.layer_norm_epsilon)
        pooled = jnp.mean(x, axis=1)
        return (pooled @ p["w"].astype(c.dtype)
                + p["b"].astype(c.dtype)).astype(jnp.float32)

    def forward(self, params, pixels):
        x = self.embed(params["embed"], pixels)
        for i, u in enumerate(self._units):
            name = self.layer_name(i + 1)
            if u[0] == "merge":
                x = self.merge(params[name], x, u[1])
            else:
                fn = self.apply_block
                if self.config.remat:
                    fn = jax.checkpoint(fn, static_argnums=(2, 3))
                x = fn(params[name], x, u[1], bool(u[2] % 2))
        return self.head(params["head"], x)

    def loss_from_logits(self, logits, batch):
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, batch["labels"][..., None], axis=-1
        )[..., 0]
        return jnp.mean(logz - gold)


    def accuracy_from_logits(self, logits, batch):
        from oobleck_tpu.models.base import argmax_accuracy

        return argmax_accuracy(logits, batch["labels"])

    def loss(self, params, batch):
        return self.loss_from_logits(
            self.forward(params, batch["pixel_values"]), batch
        )
