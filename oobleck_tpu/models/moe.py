"""Mixture-of-experts GPT: a decoder whose MLPs are switch-MoE layers.

BEYOND-reference model family (the reference has no MoE anywhere): same
LayerListModel protocol as every other family, so the MPMD engine drives it
unchanged — planning, heterogeneous pipelines, DP sync, reconfiguration,
checkpointing. The carry is a `(hidden, aux_loss)` tuple (like T5's
two-part carry): every block accumulates its Switch load-balancing loss and
the head folds `aux_weight * aux` into the objective — the generic stage
program only sees the last layer's loss, so the aux term must ride the
carry across stages (and across hosts, where the pytree-generic
cross-process edges carry it).

Expert parallelism itself lives in ops/moe.py (experts sharded over a mesh
axis, exactness-tested under shard_map); through the engine the experts are
replicated within a stage for now — honest scope, stated in PARITY.md.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp

from oobleck_tpu.models.gpt import (
    GPTConfig,
    GPTModel,
    _layer_norm,
    cross_entropy_loss,
)
from oobleck_tpu.ops.moe import switch_moe


@dataclass(frozen=True)
class MoEGPTConfig(GPTConfig):
    num_experts: int = 8
    capacity_factor: float = 1.25
    aux_weight: float = 0.01

    def override(self, **kwargs) -> "MoEGPTConfig":
        moe_keys = {k: kwargs.pop(k) for k in
                    ("num_experts", "capacity_factor", "aux_weight")
                    if k in kwargs}
        # super().override -> dataclasses.replace(self, ...) preserves the
        # subclass, so the MoE fields survive the base validation.
        return replace(super().override(**kwargs), **moe_keys)


class MoEGPTModel(GPTModel):
    """GPT decoder with switch-MoE MLPs; generic-path only (no manual-TP
    contract -> the fused SPMD step rejects it, the MPMD engine drives it)."""

    fused_supported = False

    # The manual-collective contract (and its param_specs companion) must
    # be ABSENT so PipelineInstance takes the generic stage path and
    # synthesizes replicated specs from the MoE layer shapes
    # (pipeline.py gates on hasattr for both).
    @property
    def head_loss_shifted(self):  # pragma: no cover - attribute probe
        raise AttributeError("MoE runs the generic stage path")

    @property
    def param_specs(self):  # pragma: no cover - attribute probe
        raise AttributeError("MoE uses synthesized generic specs")

    def generic_param_specs(self, li: int):
        """Expert parallelism through the engine: expert-dim leaves shard
        over the stage's fsdp axis (GSPMD runs the dispatch/combine einsums
        as true EP and inserts the combine psum itself); everything else
        replicates. The pipeline clears the axis per-stage when
        num_experts doesn't divide it."""
        from jax.sharding import PartitionSpec as P

        shapes = jax.eval_shape(
            lambda r: self.init_layer(r, li), jax.random.PRNGKey(0)
        )
        specs = jax.tree.map(lambda _: P(), shapes)
        if 0 < li < self.num_pipeline_layers - 1:
            specs["mlp"] = {
                "router": P(),
                "w1": P("fsdp"), "b1": P("fsdp"),
                "w2": P("fsdp"), "b2": P("fsdp"),
            }
        return specs

    # ---- layer list ----

    def _init_block(self, rng: jax.Array):
        c = self.config
        ks = jax.random.split(rng, 4)
        std = c.initializer_range
        base = super()._init_block(ks[0])
        ne, m, f = c.num_experts, c.hidden_size, c.ffn_dim
        # Residual output projection scaled like the dense family's
        # (std / sqrt(2L), GPT-2 discipline) so activation variance at
        # depth matches the models this variant claims to mirror.
        res_std = std / (2 * c.num_layers) ** 0.5
        base["mlp"] = {
            "router": jax.random.normal(ks[1], (m, ne), c.param_dtype) * std,
            "w1": jax.random.normal(ks[2], (ne, m, f), c.param_dtype) * std,
            "b1": jnp.zeros((ne, f), c.param_dtype),
            "w2": jax.random.normal(ks[3], (ne, f, m), c.param_dtype)
                  * res_std,
            "b2": jnp.zeros((ne, m), c.param_dtype),
        }
        return base

    def apply_layer(self, index: int, params, carry, batch,
                    ctx=None) -> Any:
        c = self.config
        last = self.num_pipeline_layers - 1
        if index == 0:
            x = super().apply_layer(0, params, None, batch)
            # Aux accumulator is [B]-shaped (a scalar carry leaf cannot
            # take the stage batch sharding P("fsdp")); blocks spread their
            # scalar aux uniformly over the batch dim and the head sums it
            # back — GSPMD inserts the cross-shard reduction when the batch
            # dim is fsdp-sharded.
            return (x, jnp.zeros((x.shape[0],), jnp.float32))
        x, aux = carry
        if index == last:
            logits = super().apply_layer(last, params, x, batch)
            # loss_from_logits unpacks the (logits, aux) pair.
            return (logits, aux)
        dt = c.dtype
        # Attention half shared with the dense family (impl dispatch,
        # ALiBi, residual) — only the MLP half is MoE-specific.
        x = self.attention_sublayer(params, x, ctx=None)
        h2 = _layer_norm(x, params["ln2"]["scale"], params["ln2"]["bias"],
                         c.layer_norm_epsilon)
        mlp = params["mlp"]
        y, block_aux = switch_moe(
            h2.astype(dt), mlp["router"], mlp["w1"], mlp["b1"],
            mlp["w2"], mlp["b2"],
            num_experts=c.num_experts,
            capacity_factor=c.capacity_factor,
        )
        return (x + y, aux + block_aux / aux.shape[0])

    def loss_from_logits(self, logits_and_aux, batch) -> jax.Array:
        logits, aux = logits_and_aux
        ce = cross_entropy_loss(logits, batch["input_ids"],
                                self.config.vocab_size)
        return ce + self.config.aux_weight * jnp.sum(aux)

    # Forward for single-device tests: chain apply_layer like the pipeline.
    def forward(self, params_list, tokens):
        batch = {"input_ids": tokens}
        carry = None
        for li in range(self.num_pipeline_layers):
            carry = self.apply_layer(li, params_list[li], carry, batch)
        return carry

    def loss(self, params_list, batch):
        out = self.forward(params_list, batch["input_ids"])
        return self.loss_from_logits(out, batch)
