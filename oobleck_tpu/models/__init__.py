"""Model registry.

Capability match for the reference's per-family AutoModel table
(/root/reference/oobleck/module/model.py:21-33): `model_name` strings resolve
to a layer-list model + config, with `model_args` overrides applied the way
the reference threads them into AutoConfig. No HF download is needed — the
architectures are defined natively — but HF-style names are accepted.
"""

from __future__ import annotations

from typing import Any, Callable

from oobleck_tpu.models import base
from oobleck_tpu.models.gpt import GPTConfig, GPTModel

_REGISTRY: dict[str, Callable[[dict[str, Any]], Any]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def _gpt(overrides: dict[str, Any], **preset) -> GPTModel:
    return GPTModel(GPTConfig().override(**preset).override(**overrides))


# GPT-2 family (HF names; sizes per the released checkpoints)
register("gpt2")(lambda o: _gpt(o, hidden_size=768, num_layers=12, num_heads=12))
register("gpt2-medium")(lambda o: _gpt(o, hidden_size=1024, num_layers=24, num_heads=16))
register("gpt2-large")(lambda o: _gpt(o, hidden_size=1280, num_layers=36, num_heads=20))
register("gpt2-xl")(lambda o: _gpt(o, hidden_size=1600, num_layers=48, num_heads=25))
# GPT-3 shapes (paper table 2.1) reachable by name, matching the reference's
# examples/gpt3.yaml trick of shaping gpt2 via model_args.
register("gpt3-1.3b")(lambda o: _gpt(o, hidden_size=2048, num_layers=24, num_heads=16, max_position_embeddings=2048))
register("gpt3-2.7b")(lambda o: _gpt(o, hidden_size=2560, num_layers=32, num_heads=32, max_position_embeddings=2048))
register("gpt3-6.7b")(lambda o: _gpt(o, hidden_size=4096, num_layers=32, num_heads=32, max_position_embeddings=2048))
# Tiny config for tests/CI.
register("gpt2-tiny")(lambda o: _gpt(o, vocab_size=256, hidden_size=64, num_layers=4, num_heads=4, max_position_embeddings=128))


def _moe(overrides: dict[str, Any], **preset):
    from oobleck_tpu.models.moe import MoEGPTConfig, MoEGPTModel

    return MoEGPTModel(MoEGPTConfig().override(**preset).override(**overrides))


# Mixture-of-experts decoders (BEYOND reference: no MoE exists there).
register("gpt2-moe")(lambda o: _moe(o, hidden_size=768, num_layers=12, num_heads=12, num_experts=8))
register("gpt2-moe-tiny")(lambda o: _moe(o, vocab_size=256, hidden_size=64, num_layers=4, num_heads=4, max_position_embeddings=128, num_experts=4))


# Bloom family: GPT architecture with ALiBi position biases (no wpe)
register("bloom-560m")(lambda o: _gpt(o, vocab_size=250880, hidden_size=1024, num_layers=24, num_heads=16, position_embedding="alibi"))
register("bloom-7b1")(lambda o: _gpt(o, vocab_size=250880, hidden_size=4096, num_layers=30, num_heads=32, position_embedding="alibi"))
register("bloom-tiny")(lambda o: _gpt(o, vocab_size=256, hidden_size=64, num_layers=4, num_heads=4, max_position_embeddings=128, position_embedding="alibi"))


def _llama(overrides, **preset):
    from oobleck_tpu.models.llama import LlamaConfig, LlamaModel

    return LlamaModel(LlamaConfig().override(**preset).override(**overrides))


# Llama family (HF names; sizes per the released checkpoints)
register("llama-2-7b")(lambda o: _llama(o, hidden_size=4096, num_layers=32, num_heads=32, intermediate_size=11008))
register("llama-2-13b")(lambda o: _llama(o, hidden_size=5120, num_layers=40, num_heads=40, intermediate_size=13824))
register("llama-3-8b")(lambda o: _llama(o, vocab_size=128256, hidden_size=4096, num_layers=32, num_heads=32, num_kv_heads=8, intermediate_size=14336, max_position_embeddings=8192, rope_theta=500000.0))
register("llama-tiny")(lambda o: _llama(o, vocab_size=256, hidden_size=64, num_layers=4, num_heads=4, num_kv_heads=2, max_position_embeddings=128))


def _bert(overrides, **preset):
    from oobleck_tpu.models.bert import BertConfig, BertModel

    return BertModel(BertConfig().override(**preset).override(**overrides))


def _vit(overrides, **preset):
    from oobleck_tpu.models.vit import ViTConfig, ViTModel

    return ViTModel(ViTConfig().override(**preset).override(**overrides))


# BERT family (bidirectional encoder, MLM objective)
register("bert-base-uncased")(lambda o: _bert(o, hidden_size=768, num_layers=12, num_heads=12))
register("bert-large-uncased")(lambda o: _bert(o, hidden_size=1024, num_layers=24, num_heads=16))
register("bert-tiny")(lambda o: _bert(o, vocab_size=256, hidden_size=64, num_layers=4, num_heads=4, max_position_embeddings=128, mask_token_id=1))

def _t5(overrides, **preset):
    from oobleck_tpu.models.t5 import T5Config, T5Model

    return T5Model(T5Config().override(**preset).override(**overrides))


# T5 family (encoder-decoder, seq2seq objective)
register("t5-base")(lambda o: _t5(o, d_model=768, num_layers=12, num_decoder_layers=12, num_heads=12, d_ff=2048))
register("t5-large")(lambda o: _t5(o, d_model=1024, num_layers=24, num_decoder_layers=24, num_heads=16, d_ff=2816))
register("t5-tiny")(lambda o: _t5(o, vocab_size=256, d_model=64, num_layers=2, num_decoder_layers=2, num_heads=4, d_ff=128))

# ViT family (image classification)
register("vit-base-patch16-224")(lambda o: _vit(o, hidden_size=768, num_layers=12, num_heads=12))
register("vit-large-patch16-224")(lambda o: _vit(o, hidden_size=1024, num_layers=24, num_heads=16))
register("vit-tiny")(lambda o: _vit(o, image_size=32, patch_size=8, num_classes=10, hidden_size=64, num_layers=4, num_heads=4))


def _resnet(overrides, **preset):
    from oobleck_tpu.models.resnet import ResNetConfig, ResNetModel

    return ResNetModel(ResNetConfig().override(**preset).override(**overrides))


# ResNet family (conv pipeline; reference sharding.py:37-41 splits per block)
register("resnet-50")(lambda o: _resnet(o, depths=(3, 4, 6, 3)))
register("resnet-152")(lambda o: _resnet(o, depths=(3, 8, 36, 3)))
register("resnet-tiny")(lambda o: _resnet(o, image_size=32, num_classes=10, embedding_size=16, hidden_sizes=(32, 64), depths=(1, 1)))


def _swin(overrides, **preset):
    from oobleck_tpu.models.swin import SwinConfig, SwinModel

    return SwinModel(SwinConfig().override(**preset).override(**overrides))


# Swin family (HF names per released checkpoints; "-micro" is the test config)
register("swin-tiny-patch4-window7-224")(lambda o: _swin(o, embed_dim=96, depths=(2, 2, 6, 2), num_heads=(3, 6, 12, 24)))
register("swin-base-patch4-window7-224")(lambda o: _swin(o, embed_dim=128, depths=(2, 2, 18, 2), num_heads=(4, 8, 16, 32)))
register("swin-micro")(lambda o: _swin(o, image_size=32, patch_size=4, num_classes=10, embed_dim=32, depths=(2, 1), num_heads=(2, 4), window_size=4))


def _clip(overrides, **preset):
    from oobleck_tpu.models.clip import CLIPConfig, CLIPModel

    return CLIPModel(CLIPConfig().override(**preset).override(**overrides))


# CLIP family (dual-encoder contrastive)
register("clip-vit-base-patch32")(lambda o: _clip(o))
register("clip-vit-base-patch16")(lambda o: _clip(o, patch_size=16))
register("clip-tiny")(lambda o: _clip(o, image_size=32, patch_size=8, vision_hidden_size=64, vision_layers=3, vision_heads=4, vocab_size=256, max_position_embeddings=32, text_hidden_size=64, text_layers=3, text_heads=4, projection_dim=32))


def build_model(model_name: str, model_args: dict[str, Any] | None = None,
                execution=None):
    """Resolve a model name (+ overrides) to a layer-list model instance.

    `execution` (an ExecutionArguments, duck-typed) threads the engine's
    precision / remat / attention_impl knobs into the model config — applied
    only where the family's config has the field, and never overriding an
    explicit `model_args` entry.
    """
    try:
        factory = _REGISTRY[model_name]
    except KeyError:
        raise ValueError(
            f"unknown model {model_name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    model_args = dict(model_args or {})
    model = factory(model_args)
    if execution is not None:
        import jax.numpy as jnp

        dtypes = {
            "bfloat16": jnp.bfloat16,
            "float32": jnp.float32,
            "float16": jnp.float16,
        }
        precision = getattr(execution, "precision", None)
        if precision is not None and precision not in dtypes:
            raise ValueError(
                f"unknown precision {precision!r}; known: {sorted(dtypes)}"
            )
        fields = type(model.config).__dataclass_fields__
        extra = {
            k: v for k, v in {
                "dtype": dtypes[precision] if precision else None,
                "remat": getattr(execution, "remat", None),
                "attention_impl": getattr(execution, "attention_impl", None),
            }.items()
            if v is not None and k in fields and k not in model_args
        }
        if extra:
            model = factory({**model_args, **extra})
    return model


def available_models() -> list[str]:
    return sorted(_REGISTRY)


__all__ = ["build_model", "available_models", "register", "base", "GPTConfig", "GPTModel"]
