"""Model registry.

Capability match for the reference's per-family AutoModel table
(/root/reference/oobleck/module/model.py:21-33): `model_name` strings resolve
to a layer-list model + config, with `model_args` overrides applied the way
the reference threads them into AutoConfig. No HF download is needed — the
architectures are defined natively — but HF-style names are accepted.
"""

from __future__ import annotations

from typing import Any, Callable

from oobleck_tpu.models import base
from oobleck_tpu.models.gpt import GPTConfig, GPTModel

_REGISTRY: dict[str, Callable[[dict[str, Any]], Any]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def _gpt(overrides: dict[str, Any], **preset) -> GPTModel:
    return GPTModel(GPTConfig().override(**preset).override(**overrides))


# GPT-2 family (HF names; sizes per the released checkpoints)
register("gpt2")(lambda o: _gpt(o, hidden_size=768, num_layers=12, num_heads=12))
register("gpt2-medium")(lambda o: _gpt(o, hidden_size=1024, num_layers=24, num_heads=16))
register("gpt2-large")(lambda o: _gpt(o, hidden_size=1280, num_layers=36, num_heads=20))
register("gpt2-xl")(lambda o: _gpt(o, hidden_size=1600, num_layers=48, num_heads=25))
# GPT-3 shapes (paper table 2.1) reachable by name, matching the reference's
# examples/gpt3.yaml trick of shaping gpt2 via model_args.
register("gpt3-1.3b")(lambda o: _gpt(o, hidden_size=2048, num_layers=24, num_heads=16, max_position_embeddings=2048))
register("gpt3-2.7b")(lambda o: _gpt(o, hidden_size=2560, num_layers=32, num_heads=32, max_position_embeddings=2048))
register("gpt3-6.7b")(lambda o: _gpt(o, hidden_size=4096, num_layers=32, num_heads=32, max_position_embeddings=2048))
# Tiny config for tests/CI.
register("gpt2-tiny")(lambda o: _gpt(o, vocab_size=256, hidden_size=64, num_layers=4, num_heads=4, max_position_embeddings=128))


def build_model(model_name: str, model_args: dict[str, Any] | None = None):
    """Resolve a model name (+ overrides) to a layer-list model instance."""
    try:
        factory = _REGISTRY[model_name]
    except KeyError:
        raise ValueError(
            f"unknown model {model_name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return factory(model_args or {})


def available_models() -> list[str]:
    return sorted(_REGISTRY)


__all__ = ["build_model", "available_models", "register", "base", "GPTConfig", "GPTModel"]
