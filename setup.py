"""Build hook: compile the native planner alongside the Python package.

The reference builds its C++ planner through CMake + pybind11
(/root/reference/setup.py:96-108); here the planner is a plain shared
library with a C API (ctypes), so the build is one compiler invocation,
also run on demand at first import (oobleck_tpu/planning/_native.py).
"""

import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithPlanner(build_py):
    def run(self):
        csrc = Path(__file__).parent / "oobleck_tpu" / "csrc"
        subprocess.run(["make", "-C", str(csrc)], check=True)
        super().run()


setup(cmdclass={"build_py": BuildWithPlanner})
